"""paddle_tpu.serving fleet router: prefix-aware multi-replica routing,
failure detection (circuit breaker), drain/failover, and the chaos
acceptance run (ISSUE 6).

Every fleet shares one fake clock; engines are seeded and decoding is
greedy, so router outputs are prefix-deterministic — the property the
mid-stream failover and the byte-identical chaos assertions lean on."""

import json
from pathlib import Path

import numpy as np
import pytest

from paddle_tpu.models import llama as L
from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                           GenerationConfig)
from paddle_tpu.observability import get_registry
from paddle_tpu.observability.events import configure_event_log
from paddle_tpu.resilience import Fault, FaultInjector
from paddle_tpu.serving import (FleetRouter, HealthConfig, HealthTracker,
                                ReplicaHandle, ReplicaState, RequestState,
                                RouterConfig, SchedulerConfig, ServingError)

REPO = Path(__file__).resolve().parent.parent


class FakeClock:
    """Deterministic fleet clock; sleep() advances it."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    def advance(self, dt):
        self.t += dt


def _fleet(n=2, max_new=4, num_slots=2, chunk=2, seed=3, page_size=4,
           eos=None, health_kw=None, router_kw=None, sched_kw=None,
           injector=None, speculative=False):
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=seed)
    clock = FakeClock()
    sched_kw = dict(sched_kw or {})
    sched_kw.setdefault("max_step_retries", 1)
    sched_kw.setdefault("retry_backoff_s", 0.01)
    replicas = []
    for i in range(n):
        eng = ContinuousBatchingEngine(
            cfg, GenerationConfig(max_new_tokens=max_new, seed=seed,
                                  eos_token_id=eos),
            num_slots=num_slots, page_size=page_size, max_seq_len=32,
            chunk=chunk, speculative=speculative)
        replicas.append(ReplicaHandle(
            i, eng, config=SchedulerConfig(**sched_kw),
            health_config=HealthConfig(**(health_kw or {})),
            clock=clock, sleep=clock.sleep))
    router = FleetRouter(replicas, config=RouterConfig(**(router_kw or {})),
                         clock=clock, sleep=clock.sleep,
                         fault_injector=injector)
    return cfg, params, router, replicas, clock


def _drive(router, clock, params, dt=0.05, max_steps=400):
    steps = 0
    while router.pending:
        router.step(params)
        clock.advance(dt)
        steps += 1
        assert steps < max_steps, router.statusz()
    return steps


def _greedy_ref(params, cfg, prompt, n_new):
    import jax.numpy as jnp
    seq = np.asarray(prompt, np.int32)[None, :]
    out = []
    for _ in range(n_new):
        logits = L.forward_stacked(params, jnp.asarray(seq), cfg)
        nxt = int(np.asarray(jnp.argmax(logits[0, -1].astype(jnp.float32))))
        out.append(nxt)
        seq = np.concatenate([seq, [[nxt]]], axis=1).astype(np.int32)
    return out


def _counter_total(name):
    m = get_registry().get(name)
    return 0.0 if m is None else m.total


# ---------------------------------------------------------------------------
# routing policy
# ---------------------------------------------------------------------------

def test_prefix_affinity_beats_load_only_within_band():
    """Same-prefix requests pile onto the replica that holds the pages
    while its load stays within load_band of the least-loaded candidate;
    past the band, queue depth wins and the request spills over."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, router_kw={"load_band": 1})
    rng = np.random.RandomState(21)
    base = rng.randint(1, cfg.vocab_size, (8,)).astype(np.int32)

    def prompt(i):
        return np.concatenate([base, [i + 1, i + 2]]).astype(np.int32)

    aff0 = _counter_total("paddle_router_prefix_affinity_hits_total")
    h0 = router.submit(prompt(0))       # cold: least-loaded tie -> r0
    h1 = router.submit(prompt(1))       # 8-token overlap, load diff 1 <= 1
    h2 = router.submit(prompt(2))       # load diff 2 > band: spills to r1
    assert [h.replica_id for h in (h0, h1, h2)] == [0, 0, 1]
    assert _counter_total(
        "paddle_router_prefix_affinity_hits_total") - aff0 == 1
    _drive(router, clock, params)
    assert all(h.state == RequestState.DONE for h in (h0, h1, h2))
    assert h2.stream.result() == _greedy_ref(params, cfg, prompt(2), 4)


def test_ejected_replica_never_receives_traffic():
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"eject_after": 1, "probe_cooldown_s": 1e9})
    replicas[0].kill()
    h_dead = router.submit(np.arange(1, 7, dtype=np.int32))
    assert h_dead.replica_id == 0       # routed before the death shows
    router.step(params)                 # r0 fails once -> EJECTED
    clock.advance(0.05)
    assert replicas[0].health.state == ReplicaState.EJECTED
    hs = [router.submit(np.arange(i, i + 6, dtype=np.int32))
          for i in range(1, 6)]
    assert all(h.replica_id == 1 for h in hs)   # no traffic to ejected
    _drive(router, clock, params)
    # the in-flight request failed over and still completed
    assert h_dead.state == RequestState.DONE and h_dead.replica_id == 1
    assert all(h.state == RequestState.DONE for h in hs)
    assert replicas[0].health.state == ReplicaState.EJECTED


def test_half_open_probe_admits_exactly_one():
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"eject_after": 1, "probe_cooldown_s": 0.2})
    replicas[0].stall(0.15)             # shorter than the cooldown
    router.submit(np.arange(1, 7, dtype=np.int32))
    router.step(params)                 # r0 raises once -> EJECTED
    clock.advance(0.05)
    assert replicas[0].health.state == ReplicaState.EJECTED
    clock.advance(0.3)                  # cooldown AND stall both lapse
    router.step(params)
    assert replicas[0].health.state == ReplicaState.HALF_OPEN
    probes = [router.submit(np.arange(i, i + 6, dtype=np.int32))
              for i in range(1, 4)]
    # exactly one request probes the half-open replica
    assert [h.replica_id for h in probes].count(0) == 1
    assert probes[0].replica_id == 0
    _drive(router, clock, params)
    # probe completed -> circuit closed, replica re-admitted
    assert replicas[0].health.state == ReplicaState.HEALTHY
    assert all(h.state == RequestState.DONE for h in probes)
    h_after = router.submit(np.arange(9, 15, dtype=np.int32))
    assert h_after.replica_id in (0, 1)     # back in rotation
    _drive(router, clock, params)


def test_failed_probe_reejects_with_doubled_cooldown():
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"eject_after": 1, "probe_cooldown_s": 0.2})
    replicas[0].kill()
    router.submit(np.arange(1, 7, dtype=np.int32))
    router.step(params)
    clock.advance(0.3)
    router.step(params)
    assert replicas[0].health.state == ReplicaState.HALF_OPEN
    h = router.submit(np.arange(2, 8, dtype=np.int32))  # becomes the probe
    assert h.replica_id == 0
    router.step(params)                 # probe step fails
    clock.advance(0.05)
    assert replicas[0].health.state == ReplicaState.EJECTED
    assert replicas[0].health.cooldown_s == pytest.approx(0.4)
    _drive(router, clock, params)
    assert h.state == RequestState.DONE and h.replica_id == 1


def test_mid_stream_failover_byte_identical(tmp_path):
    """A replica dying mid-decode: its live request resumes on a sibling
    through the retry/backoff path and the consumer stream ends with the
    exact greedy tokens of an uninterrupted run."""
    configure_event_log(str(tmp_path / "events.jsonl"))
    try:
        cfg, params, router, replicas, clock = _fleet(
            n=2, max_new=6, health_kw={"eject_after": 2,
                                       "probe_cooldown_s": 1e9})
        p = np.arange(3, 8, dtype=np.int32)
        h = router.submit(p)
        assert h.replica_id == 0
        # step until the first tokens stream (the unified ragged step
        # prefills within the step, so tokens land a round later)
        for _ in range(4):
            router.step(params)
            clock.advance(0.05)
            if h.stream.tokens:
                break
        streamed = len(h.stream.tokens)
        assert 0 < streamed < 6
        replicas[0].kill()
        _drive(router, clock, params)
        assert h.state == RequestState.DONE
        assert h.replica_id == 1 and h.failovers == 1
        assert h.stream.result() == _greedy_ref(params, cfg, p, 6)
    finally:
        configure_event_log(None)
    events = [json.loads(l) for l in
              (tmp_path / "events.jsonl").read_text().splitlines()]
    fo = [e for e in events if e["kind"] == "failover"]
    assert fo and fo[0]["from_replica"] == 0 and fo[0]["to_replica"] == 1
    assert fo[0]["streamed"] == streamed        # genuinely mid-stream
    ej = [e for e in events if e["kind"] == "replica_ejected"]
    assert ej and ej[0]["replica"] == 0


def test_fully_delivered_request_salvaged_not_failed():
    """A replica dying after streaming the LAST budgeted token but
    before the finish callback: the request closes complete even when
    no failover budget remains — the consumer already holds everything."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, router_kw={"max_failovers": 0})
    h = router.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=2)
    h.stream.push(11)
    h.stream.push(22)           # full budget delivered, close lost
    router._failover(h, 0, "died before finish callback")
    assert h.state == RequestState.DONE
    assert h.stream.result() == [11, 22]
    assert router.failed_total == 0
    # same salvage when the stream already ended on EOS short of the
    # budget: resubmitting would decode PAST the EOS on the sibling
    cfg2, params2, router2, _, _ = _fleet(
        n=2, eos=99, router_kw={"max_failovers": 3})
    h2 = router2.submit(np.arange(1, 7, dtype=np.int32),
                        max_new_tokens=6)
    h2.stream.push(42)
    h2.stream.push(99)          # EOS streamed, close lost
    router2._failover(h2, 0, "died before finish callback")
    assert h2.state == RequestState.DONE
    assert h2.stream.result() == [42, 99]


def test_graceful_drain_hands_queued_to_siblings():
    cfg, params, router, replicas, clock = _fleet(
        n=2, num_slots=1, router_kw={"load_band": 8})
    rng = np.random.RandomState(23)
    base = rng.randint(1, cfg.vocab_size, (8,)).astype(np.int32)
    hs = [router.submit(np.concatenate([base, [i + 1]]).astype(np.int32))
          for i in range(3)]
    assert all(h.replica_id == 0 for h in hs)   # affinity coalesced
    router.step(params)                 # one running, two queued on r0
    clock.advance(0.05)
    running = [h for h in hs if h.handle.state == RequestState.RUNNING]
    queued = [h for h in hs if h.handle.state == RequestState.QUEUED]
    assert len(running) == 1 and len(queued) == 2
    router.drain(0)
    # queued requests handed to the sibling immediately; the in-flight
    # stream finishes where it is
    assert all(h.replica_id == 1 for h in queued)
    assert running[0].replica_id == 0
    h_new = router.submit(np.concatenate([base, [9]]).astype(np.int32))
    assert h_new.replica_id == 1        # no new admissions while draining
    _drive(router, clock, params)
    assert all(h.state == RequestState.DONE for h in hs + [h_new])
    assert running[0].replica_id == 0   # finished in place, no failover
    assert running[0].failovers == 0
    st = router.statusz()
    assert st["replicas"]["0"]["draining"] is True
    router.undrain(0)
    assert router.fleet_health() == "ok"


def test_drain_handoff_exempt_from_sibling_queue_cap():
    """A drain handoff landing on a sibling already at its queue cap is
    remediation: the sibling sheds a FRESH request around it, never the
    handed-off one — the 'queued requests hand off to siblings' drain
    contract survives load."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, num_slots=1, sched_kw={"max_queue_depth": 1})
    rng = np.random.RandomState(27)
    ps = [rng.randint(1, cfg.vocab_size, (6,)).astype(np.int32)
          for _ in range(4)]
    h0 = router.submit(ps[0])           # -> r0 (admitted next step)
    h1 = router.submit(ps[1])           # -> r1
    router.step(params)
    clock.advance(0.05)
    h2 = router.submit(ps[2])           # queued on r0
    h3 = router.submit(ps[3])           # queued on r1 (AT its cap)
    assert h2.replica_id == 0 and h3.replica_id == 1
    router.drain(0)                     # h2 hands off to the full r1
    assert h2.replica_id == 1
    _drive(router, clock, params)
    assert h2.state == RequestState.DONE        # handoff survived
    assert h3.state == RequestState.SHED        # the fresh victim shed
    assert all(h.state == RequestState.DONE for h in (h0, h1))


def test_drain_of_half_open_replica_releases_probe_slot():
    """Draining a replica whose probe is still queued must hand the
    probe off AND clear the probe bookkeeping, so after undrain the
    replica can be probed (and re-admitted) again."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"eject_after": 1, "probe_cooldown_s": 0.2})
    replicas[0].stall(0.15)
    router.submit(np.arange(1, 7, dtype=np.int32))
    router.step(params)                 # r0 -> EJECTED
    clock.advance(0.3)
    router.step(params)                 # cooldown over -> HALF_OPEN
    h_probe = router.submit(np.arange(2, 8, dtype=np.int32))
    assert h_probe.replica_id == 0      # queued probe
    router.drain(0)                     # probe hands off to the sibling
    assert h_probe.replica_id == 1
    _drive(router, clock, params)
    assert h_probe.state == RequestState.DONE
    router.undrain(0)
    h_new = router.submit(np.arange(3, 9, dtype=np.int32))
    assert h_new.replica_id == 0        # a fresh probe is admitted
    _drive(router, clock, params)
    assert replicas[0].health.state == ReplicaState.HEALTHY


def test_router_index_capped_with_lru_eviction():
    cfg, params, router, replicas, clock = _fleet(
        n=1, router_kw={"index_max_nodes": 2})
    rng = np.random.RandomState(29)
    for _ in range(4):
        router.submit(rng.randint(1, cfg.vocab_size, (8,))
                      .astype(np.int32))
    _drive(router, clock, params)
    assert router.statusz()["index_nodes"]["0"] <= 2


def test_run_finishing_on_final_step_does_not_raise():
    cfg, params, router, replicas, clock = _fleet(n=1)
    router.submit(np.arange(1, 5, dtype=np.int32))
    steps_needed = 0
    probe = _fleet(n=1)
    probe[2].submit(np.arange(1, 5, dtype=np.int32))
    while probe[2].pending:
        probe[2].step(params)
        steps_needed += 1
    router.run(params, max_steps=steps_needed)      # exact budget: ok


def test_scheduler_degrade_treated_as_replica_death():
    """A replica whose scheduler burns its retry budget (engine step
    failing INSIDE the scheduler) is force-ejected and its requests
    fail over — the drained replica-level errors never surface to the
    router's consumers."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, sched_kw={"max_step_retries": 0},
        health_kw={"probe_cooldown_s": 1e9})
    h = router.submit(np.arange(1, 7, dtype=np.int32))
    assert h.replica_id == 0
    router.step(params)
    clock.advance(0.05)

    def always_fail(p):
        raise RuntimeError("persistent device fault")

    replicas[0].engine.step = always_fail
    _drive(router, clock, params)
    assert replicas[0].degraded
    assert replicas[0].health.state == ReplicaState.EJECTED
    assert h.state == RequestState.DONE and h.replica_id == 1
    assert h.stream.result() == _greedy_ref(
        params, cfg, np.arange(1, 7, dtype=np.int32), 4)


def test_all_replicas_down_parks_then_recovers():
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"eject_after": 1, "probe_cooldown_s": 0.2})
    replicas[0].stall(0.15)
    replicas[1].stall(0.15)
    fo0 = _counter_total("paddle_router_failovers_total")
    h0 = router.submit(np.arange(1, 7, dtype=np.int32))
    router.step(params)                 # both raise -> both EJECTED
    clock.advance(0.05)
    assert router.fleet_health() == "breached"
    h1 = router.submit(np.arange(2, 8, dtype=np.int32))
    assert h1.replica_id is None        # parked: nothing routable
    assert router.statusz()["parked"] >= 1
    clock.advance(0.3)                  # cooldowns + stalls lapse
    router.step(params)
    # half-open replicas CAN take their probes: not "breached" (a 503
    # here would let a load balancer starve the probes forever)
    assert router.fleet_health() == "degraded"
    _drive(router, clock, params)
    assert h0.state == RequestState.DONE
    assert h1.state == RequestState.DONE
    assert router.fleet_health() == "ok"
    # failovers_total counts actual sibling resubmissions: h0's parked
    # failover is counted once it finally dispatched, h1 never failed
    # over — the all-down window must not inflate the metric
    assert (_counter_total("paddle_router_failovers_total") - fo0
            == h0.failovers)


# ---------------------------------------------------------------------------
# health tracker unit behavior
# ---------------------------------------------------------------------------

def test_parked_request_deadline_beats_late_recovery():
    """A deadline that lapses while a request is parked (fleet down)
    sheds it as deadline even if a replica heals the same step — it is
    never re-routed with a zero-clamped deadline and served."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"eject_after": 1, "probe_cooldown_s": 0.2})
    replicas[0].stall(0.05)
    replicas[1].stall(0.05)
    h0 = router.submit(np.arange(1, 7, dtype=np.int32))
    router.step(params)                 # both eject; h0 parks
    clock.advance(0.05)
    h1 = router.submit(np.arange(2, 8, dtype=np.int32), deadline_ms=100)
    assert h1.replica_id is None
    clock.advance(0.5)                  # deadline AND cooldowns lapse
    router.step(params)
    assert h1.state == RequestState.SHED
    assert h1.stream.finish_reason == "shed:deadline"
    _drive(router, clock, params)
    assert h0.state == RequestState.DONE    # no deadline: probe + serve


def test_wedged_replica_trips_watchdog_and_fails_over():
    """A replica whose steps RETURN but serve nothing (engine wedged,
    no tokens, no completions) must not look healthy forever: the
    progress-gated watchdog ejects it and its requests fail over."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"suspect_after": 1, "eject_after": 2,
                        "watchdog_s": 0.2, "probe_cooldown_s": 1e9})
    h = router.submit(np.arange(1, 7, dtype=np.int32))
    assert h.replica_id == 0
    replicas[0].engine.step = lambda params: 0      # wedged, not raising
    _drive(router, clock, params, dt=0.15)
    assert replicas[0].health.state == ReplicaState.EJECTED
    assert "watchdog" in replicas[0].health.last_failure
    assert h.state == RequestState.DONE and h.replica_id == 1
    assert h.stream.result() == _greedy_ref(
        params, cfg, np.arange(1, 7, dtype=np.int32), 4)


def test_health_tracker_state_machine():
    clock = FakeClock()
    t = HealthTracker(HealthConfig(suspect_after=1, eject_after=3,
                                   probe_cooldown_s=1.0,
                                   cooldown_multiplier=2.0), clock=clock)
    assert t.state == ReplicaState.HEALTHY and t.accepting
    t.record_failure("boom")
    assert t.state == ReplicaState.SUSPECT and t.accepting
    t.record_success()
    assert t.state == ReplicaState.HEALTHY
    for _ in range(3):
        t.record_failure("boom")
    assert t.state == ReplicaState.EJECTED and not t.accepting
    clock.advance(0.5)
    assert t.tick() == ReplicaState.EJECTED     # cooldown not over
    clock.advance(0.6)
    assert t.tick() == ReplicaState.HALF_OPEN
    t.record_success()                  # idle step success: NOT enough
    assert t.state == ReplicaState.HALF_OPEN
    t.record_failure("probe died")      # probe failure: re-eject, 2x
    assert t.state == ReplicaState.EJECTED
    assert t.cooldown_s == pytest.approx(2.0)
    clock.advance(2.1)
    assert t.tick() == ReplicaState.HALF_OPEN
    t.record_probe_success()            # probe completion closes it
    assert t.state == ReplicaState.HEALTHY
    assert t.cooldown_s == pytest.approx(1.0)   # backoff reset


def test_health_tracker_watchdog():
    clock = FakeClock()
    t = HealthTracker(HealthConfig(suspect_after=1, eject_after=2,
                                   watchdog_s=1.0), clock=clock)
    t.record_success()
    clock.advance(0.5)
    assert not t.check_watchdog(busy=True)      # within the window
    clock.advance(1.0)
    assert not t.check_watchdog(busy=False)     # idle is not stuck
    assert t.check_watchdog(busy=True)          # silent + busy = failure
    assert t.state == ReplicaState.SUSPECT
    # ONE failure per silent window: an immediate re-check must not
    # double-charge (a raising replica would otherwise eject at half
    # the configured threshold)
    assert not t.check_watchdog(busy=True)
    clock.advance(1.1)                          # another full window
    assert t.check_watchdog(busy=True)
    assert t.state == ReplicaState.EJECTED
    # the watchdog window restarts at HALF_OPEN: last_ok_t froze while
    # ejected, and a stale stamp must not kill the probe before it runs
    clock.advance(t.cooldown_s + 0.1)
    assert t.tick() == ReplicaState.HALF_OPEN
    assert not t.check_watchdog(busy=True)      # fresh window
    assert t.state == ReplicaState.HALF_OPEN
    clock.advance(1.5)                          # probe silent too long
    assert t.check_watchdog(busy=True)
    assert t.state == ReplicaState.EJECTED


# ---------------------------------------------------------------------------
# fault injector: replica-scoped one-shot events
# ---------------------------------------------------------------------------

def test_fault_injector_replica_scoped_events():
    inj = FaultInjector(schedule=[
        Fault("replica_die", 3, replica=1),
        Fault("replica_stall", 2),              # unscoped wildcard
    ])
    assert not inj.fire("replica_die", 3, replica=0)    # wrong replica
    assert not inj.fire("replica_die", 2, replica=1)    # wrong step
    assert inj.fire("replica_die", 3, replica=1)
    assert not inj.fire("replica_die", 3, replica=1)    # one-shot
    # a wildcard fault is consumed by the first replica that asks
    assert inj.fire("replica_stall", 2, replica=0)
    assert not inj.fire("replica_stall", 2, replica=1)
    assert inj.fired == [("replica_die", 3, 1), ("replica_stall", 2, 0)]
    # replica-scoped faults never fire for unscoped (trainer) queries
    inj2 = FaultInjector(schedule=[Fault("step_error", 5, replica=2)])
    assert not inj2.fire("step_error", 5)
    assert inj2.fire("step_error", 5, replica=2)
    # seeded replica schedules are reproducible, 1-based (router steps
    # start at 1, so a step-0 fault could never fire), and duplicate-free
    # (the router consumes at most one triple per step, so a duplicate
    # would silently never fire)
    a = FaultInjector.seeded_replicas(7, 20, 4)
    b = FaultInjector.seeded_replicas(7, 20, 4)
    assert a.schedule == b.schedule and a.schedule
    for seed in range(16):
        sched = FaultInjector.seeded_replicas(seed, 3, 2, n_faults=6)
        assert all(1 <= f.step <= 3 for f in sched.schedule)
        assert len(set(sched.schedule)) == len(sched.schedule) == 6
    tiny = FaultInjector.seeded_replicas(0, 1, 1,
                                         events=("replica_die",),
                                         n_faults=5)
    assert len(tiny.schedule) == 1          # clamped to the fault space


# ---------------------------------------------------------------------------
# chaos acceptance
# ---------------------------------------------------------------------------

def _chaos_trace(inject, event_path=None, speculative=False):
    """One deterministic 4-replica fleet run: 12 requests submitted on a
    fixed step schedule, optionally with an injected replica death (mid-
    decode) and a stall. Returns (per-request outputs, router, monitor,
    handles)."""
    if event_path is not None:
        configure_event_log(str(event_path))
    try:
        injector = None
        if inject:
            injector = FaultInjector(schedule=[
                Fault("replica_die", 3, replica=1),
                Fault("replica_stall", 5, replica=2),
            ])
        cfg, params, router, replicas, clock = _fleet(
            n=4, max_new=8, num_slots=2, chunk=2,
            health_kw={"suspect_after": 1, "eject_after": 2,
                       "probe_cooldown_s": 0.4},
            router_kw={"failover_backoff_s": 0.05, "stall_s": 0.5},
            injector=injector, speculative=speculative)
        monitor = router.make_slo_monitor(completion_target=0.95,
                                          min_events=1)
        rng = np.random.RandomState(31)
        base = rng.randint(1, cfg.vocab_size, (4,)).astype(np.int32)
        prompts = []
        for i in range(12):
            if i % 3 == 0:      # a third share a 4-token system prefix
                tail = rng.randint(1, cfg.vocab_size, (3,))
                prompts.append(np.concatenate([base, tail])
                               .astype(np.int32))
            else:
                n = int(rng.randint(4, 9))
                prompts.append(rng.randint(1, cfg.vocab_size, (n,))
                               .astype(np.int32))
        submissions = {0: prompts[:8], 6: prompts[8:10], 16: prompts[10:]}
        handles = []
        step = 0
        while step < 300:
            for p in submissions.pop(step, []):
                handles.append(router.submit(p))
            if not submissions and not router.pending:
                break
            router.step(params)
            clock.advance(0.05)
            step += 1
        assert step < 300, router.statusz()
        outputs = [h.stream.result() for h in handles]
        return outputs, prompts, router, monitor, handles, params, cfg
    finally:
        if event_path is not None:
            configure_event_log(None)


def test_chaos_fleet_byte_identical_acceptance(tmp_path):
    """ISSUE 6 acceptance: 4-replica fleet, deterministic injected
    replica death mid-decode plus one stall — the router ejects, drains,
    fails over; every accepted request completes, greedy outputs are
    byte-identical to the fault-free run, no consumer hangs, and the
    fleet SLO never breaches (failover remediation excluded)."""
    clean, _, _, _, _, _, _ = _chaos_trace(inject=False)
    ev = tmp_path / "chaos_events.jsonl"
    chaos, prompts, router, monitor, handles, params, cfg = _chaos_trace(
        inject=True, event_path=ev)

    # every accepted request completed; zero consumer hangs
    assert all(h.state == RequestState.DONE for h in handles)
    assert all(h.stream.finished for h in handles)
    # greedy outputs byte-identical to the no-fault run
    assert chaos == clean
    # ... and to the full-reforward oracle (spot checks)
    for i in (0, 3):
        assert chaos[i] == _greedy_ref(params, cfg, prompts[i], 8)
    # no terminal failures/sheds -> fleet SLO untouched
    assert router.failed_total == 0 and router.shed_total == 0
    assert not monitor.breached()
    assert monitor.health() == "ok"

    events = [json.loads(l) for l in ev.read_text().splitlines()]
    kinds = [e["kind"] for e in events]
    ejected = [e for e in events if e["kind"] == "replica_ejected"]
    assert {e["replica"] for e in ejected} >= {1, 2}
    failovers = [e for e in events if e["kind"] == "failover"]
    assert failovers and not any(e.get("exhausted") for e in failovers)
    assert any(e["streamed"] > 0 for e in failovers)   # mid-decode death
    # the stalled replica recovered through the half-open probe
    recovered = [e for e in events if e["kind"] == "replica_recovered"]
    assert any(e["replica"] == 2 and e["via"] == "probe"
               for e in recovered)
    assert "slo_breach" not in kinds
    # the dead replica stays quarantined; the stalled one rejoined
    assert router.replicas[1].health.state in (ReplicaState.EJECTED,
                                               ReplicaState.HALF_OPEN)
    assert not router.replicas[1].health.accepting
    assert router.replicas[2].health.state == ReplicaState.HEALTHY


def test_chaos_fleet_green_with_speculation(tmp_path):
    """ISSUE 9 acceptance: the chaos suite stays green with speculative
    decoding enabled on every replica — same deterministic death+stall
    schedule, and the fleet's greedy outputs are byte-identical to BOTH
    the fault-free speculative run and the non-speculative chaos run
    (speculation is verify-then-commit, failover replays committed
    prefixes, so faults can never surface a drafted-but-unverified
    token)."""
    clean, _, _, _, _, _, _ = _chaos_trace(inject=False, speculative=True)
    plain, _, _, _, _, _, _ = _chaos_trace(inject=True)
    ev = tmp_path / "spec_chaos_events.jsonl"
    chaos, prompts, router, monitor, handles, params, cfg = _chaos_trace(
        inject=True, event_path=ev, speculative=True)

    assert all(h.state == RequestState.DONE for h in handles)
    assert all(h.stream.finished for h in handles)
    assert chaos == clean == plain
    for i in (0, 3):
        assert chaos[i] == _greedy_ref(params, cfg, prompts[i], 8)
    assert router.failed_total == 0 and router.shed_total == 0
    assert not monitor.breached()
    # speculation actually ran on the fleet (replica-labelled stats)
    drafted = sum(r.engine.spec.stats["drafted"]
                  for r in router.replicas.values())
    assert drafted > 0
    events = [json.loads(l) for l in ev.read_text().splitlines()]
    assert {e["kind"] for e in events} >= {"replica_ejected", "failover"}


def test_infeasible_request_rejected_without_poisoning_breakers():
    """A request no replica could EVER serve raises at submit (caller
    error) instead of being mistaken for replica failures and ejecting
    the whole fleet."""
    cfg, params, router, replicas, clock = _fleet(n=2)
    with pytest.raises(ValueError, match="max_seq_len"):
        router.submit(np.ones(40, np.int32))
    assert router.pending == 0
    assert all(r.health.state == ReplicaState.HEALTHY for r in replicas)
    assert router.accepted_total == 0       # never accepted


def test_diagserver_fleet_view():
    from paddle_tpu.observability.server import DiagServer
    cfg, params, router, replicas, clock = _fleet(n=2)
    srv = DiagServer()
    srv.attach_router(router)
    st = srv.statusz()
    assert st["health"] == "ok"
    assert set(st["router"]["replicas"]) == {"0", "1"}
    replicas[0].kill()
    replicas[1].kill()
    router.submit(np.arange(1, 7, dtype=np.int32))
    for _ in range(4):
        router.step(params)
        clock.advance(0.05)
    assert srv.health() == "breached"


def _fresh_handle(rid, clock, max_new=4, num_slots=2, chunk=2, seed=3,
                  page_size=4, speculative=False, sched_kw=None,
                  health_kw=None):
    """A replacement ReplicaHandle reusing ``rid`` (the
    ``replace_replica`` recovery path: fresh engine, same id — its
    ``paddle_serving_r<rid>`` namespace re-registers)."""
    cfg = L.llama_tiny(num_hidden_layers=2)
    sched_kw = dict(sched_kw or {})
    sched_kw.setdefault("max_step_retries", 1)
    sched_kw.setdefault("retry_backoff_s", 0.01)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=max_new, seed=seed),
        num_slots=num_slots, page_size=page_size, max_seq_len=32,
        chunk=chunk, speculative=speculative)
    return ReplicaHandle(rid, eng, config=SchedulerConfig(**sched_kw),
                         health_config=HealthConfig(**(health_kw or {})),
                         clock=clock, sleep=clock.sleep)


def test_replace_replica_reused_id_metrics_idempotent():
    """Satellite (ISSUE 14): a reused replica id re-registers the
    ``paddle_serving_r<id>`` metrics namespace — the registry sink must
    REPLACE (never raise on the re-declared families), the scrape must
    carry exactly one family section per name, and its values must come
    from the NEW sink. Two full replace cycles, speculation + SLO
    monitors attached, prove the whole per-replica telemetry surface is
    idempotent under id reuse."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, speculative=True, health_kw={"eject_after": 1})
    for r in replicas:
        r.make_slo_monitor()
    h = [router.submit(np.arange(1, 6, dtype=np.int32))
         for _ in range(3)]
    _drive(router, clock, params)
    assert all(q.state == RequestState.DONE for q in h)
    old_submitted = get_registry().snapshot()[
        "paddle_serving_r0"]["counters"]["requests_submitted_total"]
    assert old_submitted > 0
    for _cycle in range(2):      # two replace cycles: reuse of a reuse
        router.replicas[0].kill()
        router.eject_replica(0, "test: chip torn")
        fresh = _fresh_handle(0, clock, speculative=True,
                              health_kw={"eject_after": 1})
        router.replace_replica(fresh)       # must not raise
        fresh.make_slo_monitor()            # SLO families re-register too
    text = get_registry().prometheus_text()
    lines = [ln for ln in text.splitlines()
             if ln.startswith("paddle_serving_r0_requests_submitted_total")]
    # exactly one sample line for the family, and it reads the NEW
    # (empty) sink — not the retired one that served the storm
    assert lines == ["paddle_serving_r0_requests_submitted_total 0"], lines
    # the replaced replica serves again and its counters land in /metrics
    h2 = router.submit(np.arange(1, 6, dtype=np.int32))
    _drive(router, clock, params)
    assert h2.state == RequestState.DONE
    text = get_registry().prometheus_text()
    assert sum(ln.startswith("paddle_serving_r0_requests_submitted_total")
               for ln in text.splitlines()) == 1


def test_replace_replica_invalidates_affinity_index():
    """Satellite (ISSUE 14): the router-side radix affinity index for a
    replaced (or mesh-resized) replica must drop — a replacement engine's
    pool is COLD, so surviving synthetic page entries would route
    affinity traffic to prefixes the new pool no longer holds."""
    cfg, params, router, replicas, clock = _fleet(
        n=2, health_kw={"eject_after": 1})
    shared = np.arange(1, 13, dtype=np.int32)      # 3 full 4-token blocks
    h = [router.submit(shared) for _ in range(2)]
    _drive(router, clock, params)
    assert all(q.state == RequestState.DONE for q in h)
    warm = [rid for rid in router.replicas
            if router._overlap_tokens(rid, shared) > 0]
    assert warm, "storm should have warmed at least one index slice"
    victim = warm[0]
    assert router.statusz()["index_nodes"][str(victim)] > 0
    router.replicas[victim].kill()
    router.eject_replica(victim, "test: resize")
    router.replace_replica(_fresh_handle(victim, clock,
                                         health_kw={"eject_after": 1}))
    assert router._overlap_tokens(victim, shared) == 0
    assert router.statusz()["index_nodes"][str(victim)] == 0
    # the public invalidation hook the elastic resize controller uses
    other = [rid for rid in router.replicas if rid != victim]
    for rid in other:
        router.invalidate_index(rid)
        assert router._overlap_tokens(rid, shared) == 0
