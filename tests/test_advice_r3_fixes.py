"""Regression tests for the round-3 ADVICE findings.

Covers: int8 calibrated in_scale convention (fixed in
test_quantization.py::test_calibrated_scale_convention_matches_dynamic),
box_coder prior_box_var broadcast with axis=1, flash_attention_varlen
composing with grad(jax.jit(fn)), roi_align sampling_ratio=-1 documented
deviation tolerance, and dy2static decorator preservation.
"""

import warnings

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def test_box_coder_var_broadcast_axis1():
    """ADVICE r3 #2: decode with prior_box_var and axis=1 must scale the
    deltas with var rows paired to priors on dim 0 (same dim as the prior
    statistics), not dim 1."""
    rs = np.random.RandomState(0)
    N, M = 3, 3  # N == M so the old bug was silent, not a shape error
    prior = np.abs(rs.rand(N, 4).astype(np.float32)) + 0.5
    prior[:, 2:] += prior[:, :2] + 0.5  # valid xyxy
    var = np.abs(rs.rand(N, 4).astype(np.float32)) + 0.1
    deltas = rs.randn(N, M, 4).astype(np.float32) * 0.1

    got = np.asarray(vops.box_coder(
        paddle.to_tensor(prior), paddle.to_tensor(var),
        paddle.to_tensor(deltas), code_type="decode_center_size",
        axis=1)._value)

    # reference decode, axis=1: prior i pairs with row i of deltas
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    t = deltas * var[:, None, :]          # var follows priors on dim 0
    ocx = t[..., 0] * pw[:, None] + pcx[:, None]
    ocy = t[..., 1] * ph[:, None] + pcy[:, None]
    ow = np.exp(t[..., 2]) * pw[:, None]
    oh = np.exp(t[..., 3]) * ph[:, None]
    ref = np.stack([ocx - ow * 0.5, ocy - oh * 0.5,
                    ocx + ow * 0.5, ocy + oh * 0.5], axis=-1)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_varlen_flash_grad_of_jit():
    """ADVICE r3 #3: grad(jax.jit(fn)) over flash_attention_varlen with
    traced cu_seqlens must not fail with an escaped-tracer error."""
    from paddle_tpu.ops.flash_attention import flash_attention_varlen

    rs = np.random.RandomState(1)
    T, H, D = 24, 2, 8
    q = jnp.asarray(rs.randn(T, H, D).astype(np.float32))
    k = jnp.asarray(rs.randn(T, H, D).astype(np.float32))
    v = jnp.asarray(rs.randn(T, H, D).astype(np.float32))
    cu = jnp.asarray([0, 10, 24], jnp.int32)

    def loss(qq):
        return flash_attention_varlen(qq, k, v, cu, cu).sum()

    g_eager = jax.grad(loss)(q)
    g_jit_of_grad = jax.jit(jax.grad(loss))(q)
    g_grad_of_jit = jax.grad(jax.jit(loss))(q)   # the failing composition
    np.testing.assert_allclose(np.asarray(g_jit_of_grad),
                               np.asarray(g_eager), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(g_grad_of_jit),
                               np.asarray(g_eager), rtol=1e-4, atol=1e-4)


def test_roi_align_adaptive_ratio_tolerance():
    """ADVICE r3 #4: sampling_ratio=-1 uses a fixed 2x2 grid (documented
    deviation). For a large RoI the result must still track a dense
    explicit-ratio reference within a loose tolerance."""
    rs = np.random.RandomState(2)
    # smooth feature map so coarse sampling stays close to dense sampling
    base = rs.randn(1, 1, 4, 4).astype(np.float32)
    import jax.image
    feat = np.asarray(jax.image.resize(jnp.asarray(base), (1, 1, 32, 32),
                                       "linear"))
    boxes = np.array([[2.0, 2.0, 30.0, 30.0]], np.float32)  # 28x28 RoI
    num = np.array([1], np.int32)
    coarse = np.asarray(vops.roi_align(
        paddle.to_tensor(feat), paddle.to_tensor(boxes),
        paddle.to_tensor(num), output_size=7, sampling_ratio=-1)._value)
    dense = np.asarray(vops.roi_align(
        paddle.to_tensor(feat), paddle.to_tensor(boxes),
        paddle.to_tensor(num), output_size=7, sampling_ratio=4)._value)
    scale = np.abs(dense).max() + 1e-6
    assert np.abs(coarse - dense).max() / scale < 0.15


_DECO_CALLS = []


def _counting(fn):
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        _DECO_CALLS.append(1)
        return fn(*a, **kw)
    return wrapper


def test_dy2static_preserves_user_decorator():
    """ADVICE r3 #5: a wraps-style user decorator on a to_static target
    must still run on the compiled path (not be silently stripped). The
    decorator lives at module scope so conversion can resolve and
    re-apply it."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    @_counting
    def f(x):
        if (x.sum() > 0):
            y = x + 1
        else:
            y = x - 1
        return y

    _DECO_CALLS.clear()
    conv = convert_control_flow(f)
    out = jax.jit(conv)(jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert _DECO_CALLS, "user decorator was stripped from the compiled path"

    # converting the SAME decorated function again must stay idempotent:
    # no spurious warning, decorator still live
    _DECO_CALLS.clear()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        conv2 = convert_control_flow(f)
    assert not any("re-bound" in str(x.message) for x in w), \
        [str(x.message) for x in w]
    out2 = conv2(jnp.ones((3,)))
    np.testing.assert_allclose(np.asarray(out2), 2.0)
    assert _DECO_CALLS, "decorator lost on second conversion"


def test_dy2static_decorator_above_to_static_fires_once():
    """A decorator ABOVE @to_static stays live in the caller's chain and
    must not be re-applied to the compiled path (double-fire)."""
    from paddle_tpu.jit import to_static

    _DECO_CALLS.clear()

    @_counting
    @to_static
    def f(x):
        if (x.sum() > 0):
            y = x + 1
        else:
            y = x - 1
        return y

    out = f(paddle.to_tensor(jnp.ones((3,))))
    np.testing.assert_allclose(np.asarray(out._value), 2.0)
    assert len(_DECO_CALLS) == 1, \
        f"decorator above to_static fired {len(_DECO_CALLS)}x"


def test_int8_encoder_calibrated_range_scales_end_to_end():
    """FusedMultiTransformerInt8.from_float with range-convention
    calibrated scales must track the float stack closely (the pre-fix
    convention collapsed activations to a few int8 levels)."""
    from paddle_tpu.incubate.nn import (FusedMultiTransformer,
                                        FusedMultiTransformerInt8)
    paddle.seed(0)
    m = FusedMultiTransformer(32, 4, 64, num_layers=2)
    rs = np.random.RandomState(0)
    for plist in (m.qkv_weights, m.linear_weights, m.ffn1_weights,
                  m.ffn2_weights):
        for p in plist:
            p._value = jnp.asarray(rs.randn(*p.shape) * 0.05, jnp.float32)
    x = paddle.to_tensor(rs.randn(2, 8, 32).astype(np.float32))
    ref = np.asarray(m(x)._value)
    q = FusedMultiTransformerInt8.from_float(
        m, qkv_in_scale=[3.0, 3.0], linear_in_scale=[3.0, 3.0],
        ffn1_in_scale=[3.0, 3.0], ffn2_in_scale=[3.0, 3.0])
    got = np.asarray(q(x)._value)
    err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert err < 0.1, f"calibrated int8 encoder drifted: {err}"


def _opaque_deco(fn):
    """Wrapper that hides its reference to fn inside a list so the
    conversion-time cell re-bind cannot find it."""
    import functools
    box = [fn]

    def wrapper(*a, **kw):
        return box[0](*a, **kw)
    functools.update_wrapper(wrapper, fn)
    return wrapper


def test_dy2static_warns_when_wrapper_cannot_be_rebound():
    """A wrapper whose reference to the original function can't be
    re-bound loses its per-call behavior on the converted path — that must
    raise a warning, never happen silently."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    @_opaque_deco
    def g(x):
        if (x.sum() > 0):
            y = x * 2
        else:
            y = x * 3
        return y

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        conv = convert_control_flow(g)
        out = jax.jit(conv)(jnp.ones((2,)))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert any("dropped" in str(x.message) for x in w), \
        [str(x.message) for x in w]
