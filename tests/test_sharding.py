"""ZeRO group-sharded tests on the 8-device CPU mesh.

Mirrors the reference's test/collective/fleet hybrid_parallel_sharding_model
pattern (SURVEY.md §4): sharded training must match unsharded training
numerically; shard placement is asserted on optimizer state / params.
"""

import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.sharding import (
    group_sharded_parallel, save_group_sharded_model, shard_spec_for)
from paddle_tpu.parallel import mesh as pmesh


@pytest.fixture(autouse=True)
def reset_mesh():
    pmesh.set_global_mesh(None)
    dist.topology.set_hybrid_communicate_group(None)
    yield
    pmesh.set_global_mesh(None)
    dist.topology.set_hybrid_communicate_group(None)


def _mlp(seed=0):
    paddle.seed(seed)
    return nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16), nn.ReLU(),
        nn.Linear(16, 4))


def _data(n=5, seed=1):
    rng = np.random.RandomState(seed)
    return [(rng.randn(8, 16).astype(np.float32),
             rng.randint(0, 4, (8,)).astype(np.int64)) for _ in range(n)]


def _train(model, opt, batches):
    losses = []
    ce = nn.CrossEntropyLoss()
    for x, y in batches:
        out = model(paddle.to_tensor(x))
        loss = ce(out, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _baseline(batches, lr=0.1):
    model = _mlp()
    opt = paddle.optimizer.AdamW(learning_rate=lr, parameters=model.parameters())
    return _train(model, opt, batches), model


def test_shard_spec_for():
    assert shard_spec_for((8, 3), "sharding", 2) == P("sharding")
    assert shard_spec_for((3, 8), "sharding", 2) == P(None, "sharding")
    assert shard_spec_for((3, 5), "sharding", 2) == P()
    assert shard_spec_for((4,), "sharding", 4) == P("sharding")


@pytest.mark.parametrize("level", [
    pytest.param("os", marks=pytest.mark.slow),
    pytest.param("os_g", marks=pytest.mark.slow),
    pytest.param("p_g_os", marks=pytest.mark.slow)])
def test_group_sharded_parity(level):
    batches = _data()
    ref_losses, ref_model = _baseline(batches)

    pmesh.set_global_mesh(pmesh.build_mesh({"sharding": 4}))
    model = _mlp()
    opt = paddle.optimizer.AdamW(learning_rate=0.1,
                                 parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, level)
    losses = _train(model, opt, batches)
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)

    # final params match the unsharded run
    for (n1, p1), (n2, p2) in zip(sorted(model.named_parameters()),
                                  sorted(ref_model.named_parameters())):
        np.testing.assert_allclose(np.asarray(p1._value, np.float32),
                                   np.asarray(p2._value, np.float32),
                                   rtol=2e-4, atol=2e-5, err_msg=n1)


@pytest.mark.slow
def test_stage1_state_is_sharded():
    pmesh.set_global_mesh(pmesh.build_mesh({"sharding": 4}))
    model = _mlp()
    opt = paddle.optimizer.AdamW(learning_rate=0.1,
                                 parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, "os")
    _train(model, opt, _data(1))
    sharded = 0
    for st in opt._optim._accumulators.values():
        for k, v in st.items():
            axes = {a for d in tuple(getattr(v.sharding, "spec", P()))
                    if d is not None
                    for a in (d if isinstance(d, tuple) else (d,))}
            if "sharding" in axes:
                sharded += 1
    assert sharded > 0  # moments of the (16,32)/(32,16)/(16,4) weights shard


def test_stage3_params_sharded_and_gatherable():
    pmesh.set_global_mesh(pmesh.build_mesh({"sharding": 4}))
    model = _mlp()
    opt = paddle.optimizer.AdamW(learning_rate=0.1,
                                 parameters=model.parameters())
    wrapped, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
    specs = [p._sharding_spec for p in model.parameters()
             if p._sharding_spec is not None]
    assert specs, "stage 3 must tag params with sharding specs"
    wrapped.get_all_parameters()
    assert all(p._sharding_spec is None for p in model.parameters())


def test_save_group_sharded_model(tmp_path):
    pmesh.set_global_mesh(pmesh.build_mesh({"sharding": 4}))
    model = _mlp()
    opt = paddle.optimizer.AdamW(learning_rate=0.1,
                                 parameters=model.parameters())
    wrapped, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
    _train(wrapped, opt, _data(1))
    out = str(tmp_path / "ckpt")
    save_group_sharded_model(wrapped, out, optimizer=opt)
    state = paddle.load(out + "/model.pdmodel")
    fresh = _mlp(seed=3)
    fresh.set_state_dict(state)
    for (n, p), (_, q) in zip(sorted(fresh.named_parameters()),
                              sorted(model.named_parameters())):
        np.testing.assert_allclose(np.asarray(p._value), np.asarray(q._value),
                                   err_msg=n)


@pytest.mark.slow
def test_fleet_wraps_sharding_optimizer():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sharding_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    model = _mlp()
    opt = paddle.optimizer.AdamW(learning_rate=0.1,
                                 parameters=model.parameters())
    opt = fleet.distributed_optimizer(opt)
    from paddle_tpu.distributed.fleet.dygraph_sharding_optimizer import (
        DygraphShardingOptimizer)
    assert isinstance(opt.inner_opt, DygraphShardingOptimizer)
    r2p = opt.inner_opt._rank2params
    names = [n for ps in r2p.values() for n in ps]
    assert sorted(names) == sorted(p.name for p in model.parameters())
    # train a couple of steps end-to-end through the fleet wrapper
    losses = _train(model, opt, _data(2))
    assert all(np.isfinite(losses))
