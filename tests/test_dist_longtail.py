"""Round-5 distributed long-tail surface: gather/wait/get_backend/
destroy_process_group, object collectives (single-process + 2-process
via the launcher), shard_layer, reshard, Strategy, stream namespace.

Reference: python/paddle/distributed/communication/*.py:§0,
auto_parallel/strategy.py:§0.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestSingleProcess:
    def test_gather_matches_all_gather(self):
        x = paddle.to_tensor(np.arange(4, dtype=np.float32))
        out = dist.gather(x)
        assert len(out) >= 1
        np.testing.assert_array_equal(np.asarray(out[0]._value),
                                      [0, 1, 2, 3])

    def test_wait_and_backend(self):
        x = paddle.to_tensor(np.ones(2, np.float32))
        assert dist.wait(x) is x
        assert dist.get_backend() == "XLA"

    def test_object_collectives_single_world(self):
        lst = []
        dist.all_gather_object(lst, {"k": [1, 2]})
        assert lst == [{"k": [1, 2]}]
        ol = ["payload"]
        dist.broadcast_object_list(ol, src=0)
        assert ol == ["payload"]
        out = [None]
        dist.scatter_object_list(out, [["a"], ["b"]], src=0)
        assert out == [["a"]]

    def test_destroy_process_group_resets(self):
        dist.init_parallel_env()
        assert dist.is_initialized()
        dist.destroy_process_group()
        assert not dist.is_initialized()

    def test_strategy_shape(self):
        s = dist.Strategy({"sharding": {"enable": True, "degree": 4,
                                        "stage": 2},
                           "pipeline": {"enable": True,
                                        "accumulate_steps": 8}})
        assert s.sharding.enable and s.sharding.degree == 4
        assert s.pipeline.accumulate_steps == 8
        assert s.amp.enable is False
        assert "sharding" in repr(s)

    def test_shard_layer_replicates(self):
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.Linear(8, 2))
        before = {n: np.asarray(p._value).copy()
                  for n, p in net.named_parameters()}
        mesh = dist.ProcessMesh([0])
        dist.shard_layer(net, mesh)
        for n, p in net.named_parameters():
            np.testing.assert_array_equal(np.asarray(p._value), before[n])

    def test_shard_layer_custom_fn_and_hooks(self):
        calls = []

        def shard_fn(name, sub, mesh):
            calls.append(name)

        seen = {}

        def input_fn(inputs, mesh):
            seen["in"] = True
            return inputs

        net = paddle.nn.Linear(4, 2)
        dist.shard_layer(net, dist.ProcessMesh([0]), shard_fn,
                         input_fn=input_fn)
        assert calls  # visited at least the root layer
        net(paddle.to_tensor(np.ones((1, 4), np.float32)))
        assert seen.get("in")

    def test_shard_layer_type_checked(self):
        with pytest.raises(TypeError, match="Layer"):
            dist.shard_layer(object(), dist.ProcessMesh([0]))

    def test_reshard_exported(self):
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        mesh = dist.ProcessMesh([0])
        y = dist.reshard(x, mesh, [dist.Replicate()])
        np.testing.assert_array_equal(np.asarray(y._value),
                                      np.asarray(x._value))

    def test_stream_namespace(self):
        assert hasattr(dist.stream, "all_reduce") or hasattr(
            dist.stream, "all_gather")


PAYLOAD_OBJ = """
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import json, os
import paddle_tpu.distributed as dist

dist.init_parallel_env()
rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
objs = []
dist.all_gather_object(objs, {{"rank": rank, "payload": [rank] * 2}})
bl = [None]
if rank == 0:
    bl = [{{"from": 0}}]
dist.broadcast_object_list(bl, src=0)
out = {{"gathered": objs, "bcast": bl}}
open(os.path.join({outdir!r}, f"obj{{rank}}.json"), "w").write(
    json.dumps(out))
"""


@pytest.mark.slow
def test_object_collectives_two_procs(tmp_path):
    """all_gather_object / broadcast_object_list across two launcher
    processes, exchanging over the jax coordination service."""
    payload = tmp_path / "payload.py"
    payload.write_text(PAYLOAD_OBJ.format(repo=REPO, outdir=str(tmp_path)))
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2", "--log_dir", str(tmp_path / "log"),
           str(payload)]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=180)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    for rank in range(2):
        data = json.loads((tmp_path / f"obj{rank}.json").read_text())
        got = sorted(data["gathered"], key=lambda d: d["rank"])
        assert got == [{"rank": 0, "payload": [0, 0]},
                       {"rank": 1, "payload": [1, 1]}]
        assert data["bcast"] == [{"from": 0}]
