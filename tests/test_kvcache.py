"""Prefix cache for the paged KV pool (paddle_tpu.kvcache): radix index,
refcounted shared ownership, copy-on-write, LRU eviction — and the e2e
acceptance bar: byte-identical generation with the cache enabled vs
disabled, >=50% of prefill tokens skipped on warm shared-prefix traffic,
and the page conservation invariant holding after every engine step."""

import re

import numpy as np
import pytest

from paddle_tpu.kvcache import (LRUEvictionPolicy, PrefixCache,
                                RefcountedKVCacheManager, RadixTree)



def _mgr(num_pages=12, page_size=4):
    # tiny device arrays: 1 layer, 1 kv head, dim 2 — metadata is the test
    return RefcountedKVCacheManager(1, num_pages, page_size, 1, 2)


def _toks(*blocks):
    out = []
    for b in blocks:
        out.extend(b)
    return out


# ---------------------------------------------------------------------------
# radix tree
# ---------------------------------------------------------------------------

def test_radix_match_full_blocks_only():
    t = RadixTree(page_size=4)
    t.insert([1, 2, 3, 4, 5, 6, 7, 8], [10, 11])
    assert [n.page for n in t.match([1, 2, 3, 4, 5, 6, 7, 8, 9])] == [10, 11]
    # divergence after one block
    assert [n.page for n in t.match([1, 2, 3, 4, 9, 9, 9, 9])] == [10]
    # partial block never matches
    assert t.match([1, 2, 3]) == []
    assert t.match([2, 2, 3, 4]) == []


def test_radix_insert_reports_duplicates_not_adoption():
    t = RadixTree(page_size=2)
    adopted, dup = t.insert([1, 2, 3, 4], [5, 6])
    assert (adopted, dup) == ([5, 6], [])
    # same blocks under different pages: nothing adopted, dups reported
    adopted, dup = t.insert([1, 2, 3, 4, 9, 9], [7, 8, 9])
    assert adopted == [9] and dup == [7, 8]
    assert len(t) == 3


def test_radix_remove_leaf_only():
    t = RadixTree(page_size=2)
    t.insert([1, 2, 3, 4], [5, 6])
    inner = t.match([1, 2])[0]
    with pytest.raises(ValueError):
        t.remove(inner)
    leaf = t.match([1, 2, 3, 4])[-1]
    t.remove(leaf)
    assert t.match([1, 2, 3, 4]) == [inner]
    t.remove(inner)          # now a leaf
    assert len(t) == 0


# ---------------------------------------------------------------------------
# refcounted pool
# ---------------------------------------------------------------------------

def test_shared_allocation_refcounts_and_release():
    mgr = _mgr(num_pages=8, page_size=4)
    a = mgr.allocate("a", 8)                       # 2 owned pages
    b = mgr.allocate("b", 12, shared=a)            # shares both + 1 fresh
    assert b[:2] == a and len(b) == 3
    assert mgr.refcount(a[0]) == 2 and mgr.refcount(b[2]) == 1
    mgr.free("a")
    assert mgr.refcount(a[0]) == 1                 # b still holds them
    mgr.check_conservation()
    mgr.free("b")
    assert mgr.num_free_pages == mgr.usable_pages  # nothing cached
    mgr.check_conservation()


def test_cached_pages_survive_release_and_evict_to_free():
    mgr = _mgr(num_pages=6, page_size=4)
    pages = mgr.allocate("a", 8)
    for p in pages:
        mgr.adopt_cached(p)
    mgr.free("a")
    assert mgr.num_free_pages == mgr.usable_pages - 2
    assert mgr.num_cached_pages == 2
    mgr.check_conservation()
    mgr.evict_cached(pages[0])
    assert mgr.num_free_pages == mgr.usable_pages - 1
    mgr.check_conservation()


def test_conservation_detects_violations():
    mgr = _mgr()
    mgr.allocate("a", 4)
    # corruption injection MUST bypass the public surface — that is the
    # point of the test  # tpu-lint: disable=private-kvcache
    mgr._free.append(mgr._tables["a"][0])          # free a live page
    with pytest.raises(RuntimeError, match="overlap"):
        mgr.check_conservation()
    mgr = _mgr()
    mgr.allocate("a", 4)
    mgr._tables.pop("a")                           # leak: refs != tables
    with pytest.raises(RuntimeError, match="diverge"):
        mgr.check_conservation()


def test_copy_page_copies_device_content():
    import jax.numpy as jnp
    mgr = _mgr(num_pages=6, page_size=4)
    src, dst = 1, 2
    mgr.k_pages = mgr.k_pages.at[:, src].set(7.0)
    mgr.v_pages = mgr.v_pages.at[:, src].set(3.0)
    mgr.copy_page(src, dst)
    assert float(jnp.abs(mgr.k_pages[:, dst] - 7.0).max()) == 0.0
    assert float(jnp.abs(mgr.v_pages[:, dst] - 3.0).max()) == 0.0


# ---------------------------------------------------------------------------
# prefix cache orchestration
# ---------------------------------------------------------------------------

def test_lookup_caps_full_prompt_match_with_cow():
    mgr = _mgr(num_pages=12, page_size=4)
    cache = PrefixCache(mgr)
    prompt = list(range(1, 9))                     # exactly 2 blocks
    table = mgr.allocate(0, 8)
    cache.insert(prompt, table)
    mgr.free(0)
    shared, n_cached, cow = cache.lookup(prompt)
    # full match: last page goes copy-on-write, one token recomputed
    assert shared == table[:1] and n_cached == 7 and cow == table[1]
    # longer prompt with the same prefix: plain 2-page share, no COW
    shared, n_cached, cow = cache.lookup(prompt + [77])
    assert shared == table and n_cached == 8 and cow is None


def test_lru_eviction_prefers_coldest_leaf():
    mgr = _mgr(num_pages=12, page_size=4)
    cache = PrefixCache(mgr)
    pa = _toks(range(4), range(4))                 # prefix A: 2 blocks
    pb = _toks(range(10, 14), range(20, 24))       # prefix B: 2 blocks
    ta = mgr.allocate("a", 8)
    cache.insert(pa, ta)
    mgr.free("a")
    tb = mgr.allocate("b", 8)
    cache.insert(pb, tb)
    mgr.free("b")
    cache.lookup(pa + [9])                         # touch A: B is now LRU
    assert cache.evict(1) == 1
    # B's leaf died; A fully resident
    assert len(cache.tree.match(pb, touch=False)) == 1
    assert len(cache.tree.match(pa, touch=False)) == 2
    mgr.check_conservation()


def test_evict_respects_protect_and_pinned_pages():
    mgr = _mgr(num_pages=12, page_size=4)
    cache = PrefixCache(mgr)
    prompt = _toks(range(4), range(4))
    table = mgr.allocate("a", 8)
    cache.insert(prompt, table)
    mgr.free("a")
    # protected pages never die, so only the unprotected leaf can go
    assert cache.evict(5, protect=table) == 0
    # pin via a live sharer: nothing evictable at all
    mgr.allocate("b", 8, shared=table)
    assert cache.evict(5) == 0
    mgr.free("b")
    assert cache.evict(5) == 2
    assert mgr.num_free_pages == mgr.usable_pages
    mgr.check_conservation()


# ---------------------------------------------------------------------------
# satellite: randomized interleaving property test
# ---------------------------------------------------------------------------

def test_pool_invariants_random_interleavings():
    """submit/draft(grow+verify/rollback)/extend/accept/reject/cancel/
    retire/evict in random order: conservation holds after every op,
    refcounts never negative (check_conservation cross-checks refs
    against block-table occupancy, so a page in two tables with a dead
    refcount cannot hide). The ``spec`` op is the speculative row's
    lifecycle at pool level: grow the table for a drafted span past the
    committed length, then commit a random prefix and truncate the rest
    — exactly what the engine's verify/rollback does per row.

    The HBM ledger rides along: after EVERY op (mid-draft grow/truncate
    included) its byte conservation audit — free + live + spec + cached
    bytes == pool bytes — must balance too, with speculative tails
    (pages past each sequence's committed length) split into their own
    class."""
    from paddle_tpu.observability.memory import MemoryLedger
    for seed in (0, 1, 2):
        rng = np.random.RandomState(seed)
        mgr = _mgr(num_pages=16, page_size=2)
        cache = PrefixCache(mgr)
        led = MemoryLedger()
        live = {}
        next_sid = 0

        def audit_bytes():
            # committed reservation per live sequence: pages covering
            # its committed length — anything beyond is a drafted tail
            led.observe(mgr, reserved={
                sid: mgr.pages_for(mgr.seq_len(sid)) for sid in live})

        for _ in range(300):
            op = rng.choice(["submit", "extend", "retire", "cancel",
                             "evict", "spec"],
                            p=[0.3, 0.1, 0.2, 0.1, 0.1, 0.2])
            if op == "submit":
                lp = int(rng.randint(1, 9))
                prompt = [int(t) for t in rng.randint(0, 3, lp)]
                budget = int(rng.randint(1, 5))
                total = lp + budget
                if mgr.pages_for(total) > mgr.usable_pages:
                    continue
                shared, n_cached, cow = cache.lookup(prompt)
                need = mgr.pages_for(total) - len(shared)
                if mgr.num_free_pages < need:
                    cache.evict(need - mgr.num_free_pages,
                                protect=shared + [cow])
                if mgr.num_free_pages < need and cow is not None:
                    cow, n_cached = None, len(shared) * mgr.page_size
                    cache.evict(need - mgr.num_free_pages, protect=shared)
                if mgr.num_free_pages < need:
                    continue                     # engine would defer
                table = mgr.allocate(next_sid, total, shared=shared)
                if cow is not None:
                    mgr.copy_page(cow, table[len(shared)])
                live[next_sid] = {"prompt": prompt, "gen": [],
                                  "budget": budget}
                next_sid += 1
            elif op == "extend" and live:
                sid = int(rng.choice(list(live)))
                try:
                    mgr.extend(sid, 1)
                except MemoryError:
                    cache.evict(1)
                    try:
                        mgr.extend(sid, 1)
                    except MemoryError:
                        continue             # genuinely full: defer
                live[sid]["gen"].append(int(rng.randint(0, 3)))
            elif op == "retire" and live:
                sid = int(rng.choice(list(live)))
                st = live.pop(sid)
                cache.insert(st["prompt"] + st["gen"], mgr._tables[sid])
                mgr.free(sid)
            elif op == "cancel" and live:
                sid = int(rng.choice(list(live)))
                live.pop(sid)
                mgr.free(sid)                    # cancelled: no insert
            elif op == "evict":
                cache.evict(int(rng.randint(1, 4)))
            elif op == "spec" and live:
                sid = int(rng.choice(list(live)))
                cur = mgr.seq_len(sid)
                span = int(rng.randint(1, 6))
                try:
                    mgr.grow_to(sid, cur + span)     # draft the span
                except MemoryError:
                    cache.evict(mgr.pages_for(cur + span)
                                - len(mgr._tables[sid]))
                    try:
                        mgr.grow_to(sid, cur + span)
                    except MemoryError:
                        mgr.check_conservation()
                        audit_bytes()
                        continue                 # engine clamps instead
                mgr.check_conservation()         # mid-draft books balance
                audit_bytes()                    # ... in bytes too (the
                # drafted tail shows up as kv_spec until the verify)
                tail = mgr.pages_for(cur + span) - mgr.pages_for(cur)
                assert led.class_bytes("kv_spec") == \
                    tail * mgr.page_nbytes
                accepted = int(rng.randint(0, span + 1))
                committed = cur + accepted
                # verify: commit the accepted prefix, roll the rest back
                mgr.truncate_pages(sid, mgr.pages_for(committed))
                mgr._lens[sid] = committed
                live[sid]["gen"].extend(
                    int(t) for t in rng.randint(0, 3, accepted))
            mgr.check_conservation()
            audit_bytes()
        for sid in list(live):
            mgr.free(sid)
        live.clear()
        mgr.check_conservation()
        audit_bytes()
        assert led.class_bytes("kv_live") == 0
        assert led.class_bytes("kv_spec") == 0
        # everything unreferenced: full eviction must drain to all-free
        cache.evict(mgr.usable_pages)
        assert mgr.num_free_pages == mgr.usable_pages
        led.observe(mgr)
        assert led.class_bytes("kv_free") == \
            mgr.usable_pages * mgr.page_nbytes


# ---------------------------------------------------------------------------
# engine integration (e2e acceptance)
# ---------------------------------------------------------------------------

def _setup_engine(prefix_cache, max_new=6, num_slots=2, num_pages=None,
                  seed=3):
    from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                               GenerationConfig)
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=seed)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=max_new),
        num_slots=num_slots, page_size=4, max_seq_len=32, chunk=3,
        num_pages=num_pages, prefix_cache=prefix_cache)
    return cfg, params, eng


def _shared_prefix_prompts(cfg, n=4, sys_len=12, seed=0):
    rng = np.random.RandomState(seed)
    sys_p = rng.randint(1, cfg.vocab_size, (sys_len,)).astype(np.int32)
    return [np.concatenate([sys_p,
                            rng.randint(1, cfg.vocab_size,
                                        (int(rng.randint(2, 8)),)
                                        ).astype(np.int32)])
            for _ in range(n)]


def test_generation_byte_identical_cache_on_vs_off():
    """THE acceptance bar: same prompts, same seed — the cache-enabled
    engine (cold AND warm waves, COW included) produces exactly the
    token lists of the cache-disabled engine."""
    cfg, params, eng_off = _setup_engine(prefix_cache=False)
    _, _, eng_on = _setup_engine(prefix_cache=True)
    prompts = _shared_prefix_prompts(cfg)
    # one prompt of exactly 4 pages forces the full-match COW path on
    # its second wave
    prompts.append(prompts[0][:16])
    assert len(prompts[-1]) == 16
    for wave in range(2):
        expect = eng_off.serve(params, prompts)
        got = eng_on.serve(params, prompts)
        assert got == expect, f"wave {wave} diverged"
    st = eng_on.cache.snapshot()
    assert st["hits"] > 0 and st["cow_copies"] > 0
    assert st["cached_tokens"] > 0
    eng_on.mgr.check_conservation()


def test_warm_wave_skips_half_the_prefill_tokens():
    """Shared-system-prompt traffic: the warm wave computes < 50% of the
    prefill tokens the cold wave did (>= 50% skipped)."""
    cfg, params, eng = _setup_engine(prefix_cache=True)
    prompts = _shared_prefix_prompts(cfg, sys_len=16)
    eng.serve(params, prompts)
    cold = eng._prefill_tokens
    eng.serve(params, prompts)
    warm = eng._prefill_tokens - cold
    assert warm <= cold / 2, (cold, warm)
    assert eng.cache.stats["hits"] >= len(prompts)


def test_cache_disabled_engine_unchanged():
    """prefix_cache=False keeps the plain manager: no cache attribute
    consulted, no refcount bookkeeping."""
    from paddle_tpu.ops.paged_attention import PagedKVCacheManager
    _, _, eng = _setup_engine(prefix_cache=False)
    assert eng.cache is None
    assert type(eng.mgr) is PagedKVCacheManager


def test_over_reject_uses_whole_pool_capacity():
    """Satellite fix: a request bigger than the WHOLE pool raises; one
    that merely exceeds the transient free count (pool full of cached
    pages) evicts and admits instead of raising."""
    cfg, params, eng = _setup_engine(prefix_cache=True, max_new=4,
                                     num_slots=1, num_pages=7)
    rng = np.random.RandomState(1)
    # fill the cache: one request retires and leaves its pages cached
    p0 = rng.randint(1, cfg.vocab_size, (12,)).astype(np.int32)
    eng.serve(params, [p0])
    assert eng.mgr.num_cached_pages > 0
    free_before = eng.mgr.num_free_pages
    # needs more than the free count but fits the pool: must evict, admit
    p1 = rng.randint(1, cfg.vocab_size, (20,)).astype(np.int32)
    assert eng.mgr.pages_for(len(p1) + 4) > free_before
    out = eng.serve(params, [p1])
    assert len(out[0]) == 4
    assert eng.cache.stats["evictions"] > 0
    # permanently infeasible: beyond usable_pages raises MemoryError
    eng.submit(rng.randint(1, cfg.vocab_size, (28,)).astype(np.int32))
    with pytest.raises(MemoryError, match="pool only holds"):
        eng.step(params)


def test_scheduler_charges_uncached_suffix_and_reports_gauges():
    """ServingScheduler over a cache-enabled engine: warm requests admit
    against suffix-only page budgets, and the cached/live gauge split is
    sampled."""
    from paddle_tpu.serving import SchedulerConfig, ServingScheduler
    cfg, params, eng = _setup_engine(prefix_cache=True)
    prompts = _shared_prefix_prompts(cfg)
    sched = ServingScheduler(eng, SchedulerConfig(max_queue_depth=32))
    handles = [sched.submit(p) for p in prompts]    # cold wave
    sched.run(params, max_steps=1000)
    handles += [sched.submit(p) for p in prompts]   # warm wave
    sched.run(params, max_steps=1000)
    assert all(h.done for h in handles)
    assert eng.cache.stats["hits"] >= len(prompts)
    g = sched.metrics.gauges
    assert "cached_page_utilization" in g and "live_page_utilization" in g
    assert g["cached_page_utilization"] > 0.0      # retired prefixes resident
    # registry carries the kvcache counters + page-state gauge split
    from paddle_tpu.observability import get_registry
    text = get_registry().prometheus_text()
    assert re.search(r"paddle_kvcache_hits_total [1-9]", text)
    assert 'paddle_kvcache_pages{state="cached"}' in text


def test_cache_hit_and_evict_events_logged(tmp_path):
    from paddle_tpu.observability.events import configure_event_log
    import json
    path = str(tmp_path / "events.jsonl")
    configure_event_log(path)
    try:
        cfg, params, eng = _setup_engine(prefix_cache=True, num_slots=1,
                                         num_pages=9, max_new=4)
        rng = np.random.RandomState(2)
        p = rng.randint(1, cfg.vocab_size, (10,)).astype(np.int32)
        eng.serve(params, [p])
        eng.serve(params, [p])                     # hit
        big = rng.randint(1, cfg.vocab_size, (20,)).astype(np.int32)
        eng.serve(params, [big])                   # pressure -> evict
    finally:
        configure_event_log(None)
    kinds = [json.loads(l)["kind"] for l in open(path)]
    assert "cache_hit" in kinds and "cache_evict" in kinds


# ---------------------------------------------------------------------------
# lint: pool internals stay behind the ops/kvcache boundary
# ---------------------------------------------------------------------------

def test_no_private_pool_access_outside_ops_and_kvcache():
    """Forbid `._free` / `._pages_for` outside paddle_tpu/ops/ and
    paddle_tpu/kvcache/: every other layer sizes requests via the public
    ``pages_for()``/``usable_pages`` surface, and only the pool itself
    touches the free list (the refcount/cached states make direct free-
    list surgery unsound). Ported to tpu-lint (rule ``private-kvcache``
    — AST attribute analysis, so this file no longer needs to exclude
    itself: the deliberate corruption-injection above carries an inline
    ``# tpu-lint: disable=`` instead)."""
    from paddle_tpu import analysis
    bad = analysis.cached_report().new_for_rule("private-kvcache")
    assert not bad, (
        "private page-pool access:\n" + "\n".join(f.text() for f in bad)
        + "\nuse pages_for()/usable_pages, or route page ownership "
        "through paddle_tpu.kvcache")
