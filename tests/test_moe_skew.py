"""MoE hot-expert stress (VERDICT r4 next-round #8): skewed routing where
~90% of tokens hit 2 experts. Checks capacity-drop accounting, no-NaN with
empty experts, EP-vs-dense parity under skew, and finite training grads.

Reference: capacity kernels limit_by_capacity / prune_gate_by_capacity
(paddle/phi/kernels/gpu/limit_by_capacity_kernel.cu:§0, SURVEY §2.4 EP row)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops import moe_ops as mo
from paddle_tpu.core.compat import shard_map


def _skewed_logits(rs, T, E, hot=(0, 1), hot_frac=0.9):
    """Gate logits sending ~hot_frac of tokens to the hot experts."""
    logits = rs.randn(T, E).astype(np.float32)
    n_hot = int(T * hot_frac)
    for i in range(n_hot):
        logits[i, hot[i % len(hot)]] += 8.0
    return logits


class TestSkewAccounting:
    def test_capacity_drop_accounting(self):
        """Under 90/10 skew with a small capacity: every expert's kept
        slots <= capacity, kept+dropped == routed, and dropped tokens
        contribute exactly zero to the combined output."""
        rs = np.random.RandomState(0)
        T, E, C, D = 64, 8, 6, 4
        logits = _skewed_logits(rs, T, E)
        probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        gate_prob, gate_idx = jax.lax.top_k(probs, 2)
        routes = mo.dispatch_indices_topk(np.asarray(gate_idx), E, C)
        tfs, cfs, flats, oks = mo.dispatch_plan(routes, E, C, T)

        # per-slot occupancy: token_for_slot >= 0
        tfs_np = np.asarray(tfs).reshape(E, C)
        kept_per_expert = (tfs_np >= 0).sum(axis=1)
        assert (kept_per_expert <= C).all()
        # routed = every (token, k) pair; kept = slots that landed
        counts = np.zeros(E, np.int64)
        for t in range(T):
            for k in range(2):
                counts[int(np.asarray(gate_idx)[t, k])] += 1
        np.testing.assert_array_equal(kept_per_expert,
                                      np.minimum(counts, C))
        # hot experts overflow, cold experts keep everything
        assert kept_per_expert[0] == C and kept_per_expert[1] == C
        assert counts[0] > C and counts[1] > C

        # dropped tokens: combine contribution is zero -> with identity
        # experts the output for fully-dropped tokens is exactly 0
        x = rs.randn(T, D).astype(np.float32)
        slots = mo.moe_dispatch_gather(jnp.asarray(x), tfs, flats, oks, E, C)
        out = mo.moe_combine_gather(slots, gate_prob, flats, oks, tfs, cfs)
        out = np.asarray(out)
        # oks (T, K) flags which routes landed within capacity: with
        # identity experts every token's output is x[t] * sum of kept
        # route probs — dropped routes contribute exactly zero
        ok_np = np.asarray(oks)
        gp = np.asarray(gate_prob)
        for t in range(T):
            w = sum(float(gp[t, k]) for k in range(2) if ok_np[t, k])
            np.testing.assert_allclose(out[t], x[t] * w, rtol=1e-5,
                                       atol=1e-6)

    def test_empty_experts_no_nan(self):
        """All tokens routed to expert 0: the other experts run on empty
        (masked) slots — forward and grads must stay finite."""
        rs = np.random.RandomState(1)
        T, E, C, D, FF = 32, 8, 32, 4, 8
        x = rs.randn(T, D).astype(np.float32)
        logits = np.full((T, E), -10.0, np.float32)
        logits[:, 0] = 10.0
        w1 = (rs.randn(E, D, FF) * 0.3).astype(np.float32)
        w2 = (rs.randn(E, FF, D) * 0.3).astype(np.float32)

        def loss(xv, w1v, w2v):
            probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
            gate_prob, gate_idx = jax.lax.top_k(probs, 2)
            routes = mo.dispatch_indices_topk(gate_idx, E, C)
            tfs, cfs, flats, oks = mo.dispatch_plan(routes, E, C, T)
            slots = mo.moe_dispatch_gather(xv, tfs, flats, oks, E, C)
            h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", slots, w1v))
            y = jnp.einsum("ecf,efd->ecd", h, w2v)
            out = mo.moe_combine_gather(y, gate_prob, flats, oks, tfs, cfs)
            return jnp.sum(out ** 2)

        val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
            jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2))
        assert np.isfinite(float(val))
        for g in grads:
            assert np.isfinite(np.asarray(g)).all()
        # empty experts must receive exactly zero weight gradient
        gw1 = np.asarray(grads[1])
        assert np.abs(gw1[2:]).max() == 0.0

    def test_ep_matches_dense_under_skew(self):
        """8-device expert-parallel all_to_all path == single-device gather
        path under 90/10 skew WITH drops (same capacity on both)."""
        E, D, FF, T_local = 8, 4, 16, 32
        n = 8
        T = n * T_local
        C = 8   # tight: hot experts drop
        mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:n]), ("expert",))
        from jax.sharding import PartitionSpec as P
        rs = np.random.RandomState(2)
        x = rs.randn(T, D).astype(np.float32)
        logits = _skewed_logits(rs, T, E)
        w1 = (rs.randn(E, D, FF) * 0.3).astype(np.float32)
        w2 = (rs.randn(E, FF, D) * 0.3).astype(np.float32)

        def fn(xl, lg, w1l, w2l):
            return mo.expert_parallel_ffn(xl, lg, w1l, w2l, "expert",
                                          num_experts=E, capacity=C, topk=2)

        f = jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(P("expert"), P("expert"), P("expert"), P("expert")),
            out_specs=P("expert"), check_vma=False))
        got = np.asarray(f(x, logits, w1, w2))
        assert np.isfinite(got).all()

        # single-device oracle: same routing/capacity per LOCAL shard
        # (capacity applies per source device in the EP path)
        outs = []
        for dvc in range(n):
            xl = jnp.asarray(x[dvc * T_local:(dvc + 1) * T_local])
            lg = jnp.asarray(logits[dvc * T_local:(dvc + 1) * T_local])
            probs = jax.nn.softmax(lg, axis=-1)
            gate_prob, gate_idx = jax.lax.top_k(probs, 2)
            routes = mo.dispatch_indices_topk(gate_idx, E, C)
            tfs, cfs, flats, oks = mo.dispatch_plan(routes, E, C, T_local)
            slots = mo.moe_dispatch_gather(xl, tfs, flats, oks, E, C)
            h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf",
                                       slots.astype(jnp.float32),
                                       jnp.asarray(w1)))
            y = jnp.einsum("ecf,efd->ecd", h, jnp.asarray(w2))
            out = mo.moe_combine_gather(y, gate_prob, flats, oks, tfs, cfs)
            outs.append(np.asarray(out))
        ref = np.concatenate(outs, axis=0)
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-4)

    def test_moe_layer_trains_under_skew(self):
        """GPT-MoE block with a gate biased 90/10: one training step runs,
        loss and every grad finite (capacity drops do not poison AD)."""
        from paddle_tpu.incubate.distributed.models.moe.moe_layer import \
            MoELayer
        from paddle_tpu import nn, optimizer

        d = 8
        paddle.seed(0)

        class _Expert(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(d, d)

            def forward(self, x):
                return self.fc(x)

        layer = MoELayer(d, [_Expert() for _ in range(4)], gate="gshard",
                         top_k=2)
        # bias the gate hard toward experts 0/1
        for name, p in layer.named_parameters():
            if "gate" in name and p.ndim == 2:
                v = np.asarray(p._value).copy()
                v[:, 0] += 4.0
                v[:, 1] += 3.5
                p.set_value(v)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=layer.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(64, d).astype(np.float32))
        out = layer(x)
        loss = (out ** 2).mean()
        loss.backward()
        for p in layer.parameters():
            if p._grad_value is not None:
                assert np.isfinite(np.asarray(p._grad_value)).all()
        opt.step()
        assert np.isfinite(float(loss._value))
