"""Distributed engine tests on the 8-device CPU mesh (SURVEY.md §4 (c))."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.parallel import mesh as pmesh, pipeline as ppipe, pcontext
from paddle_tpu.core.compat import shard_map


@pytest.fixture(autouse=True)
def reset_mesh():
    pmesh.set_global_mesh(None)
    dist.topology.set_hybrid_communicate_group(None)
    yield
    pmesh.set_global_mesh(None)
    dist.topology.set_hybrid_communicate_group(None)


def test_mesh_build():
    m = pmesh.build_mesh({"dp": 2, "mp": 4})
    assert m.shape["dp"] == 2 and m.shape["mp"] == 4 and m.shape["pp"] == 1
    m2 = pmesh.build_mesh({})  # all into dp
    assert m2.shape["dp"] == 8


def test_collectives_all_reduce():
    pmesh.set_global_mesh(pmesh.build_mesh({"dp": 8}))
    g = dist.new_group(axis="dp")
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    dist.all_reduce(x, group=g)
    np.testing.assert_allclose(x.numpy(), np.full(8, 28.0))


def test_collectives_all_gather_and_reduce_scatter():
    pmesh.set_global_mesh(pmesh.build_mesh({"dp": 8}))
    g = dist.new_group(axis="dp")
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    # global-array semantics: each rank's "tensor" is its dim-0 shard, so
    # all_gather reconstitutes the global array (now replicated everywhere)
    out = dist.all_gather(x, group=g)
    assert out.shape == [8]
    np.testing.assert_allclose(out.numpy(), x.numpy())
    # reduce_scatter: replicated input, output = summed tensor scattered
    rs = dist.reduce_scatter(paddle.to_tensor(np.ones(8, np.float32)), group=g)
    np.testing.assert_allclose(rs.numpy(), np.full(8, 8.0))


def test_alltoall():
    pmesh.set_global_mesh(pmesh.build_mesh({"dp": 8}))
    g = dist.new_group(axis="dp")
    x = paddle.to_tensor(np.arange(64, dtype=np.float32))
    out = dist.alltoall(x, group=g)
    assert out.shape == [64]
    # alltoall twice = identity
    back = dist.alltoall(out, group=g)
    np.testing.assert_allclose(back.numpy(), x.numpy())


def test_fleet_init_topology():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    topo = hcg.topology()
    assert topo.world_size() == 8
    # rank->coord bijection
    assert topo.get_coord(0) == (0, 0, 0, 0, 0)
    groups = topo.get_comm_list("mp")
    assert len(groups) == 4 and all(len(g) == 2 for g in groups)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _run_training(step_builder, n=6):
    paddle.seed(11)
    net = MLP()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters(),
                                 grad_clip=paddle.optimizer.ClipGradByGlobalNorm(1.0))
    step = step_builder(net, opt)
    rng = np.random.RandomState(5)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = rng.randint(0, 4, size=(16,)).astype(np.int64)
    losses = []
    for _ in range(n):
        losses.append(float(step(paddle.to_tensor(xs), paddle.to_tensor(ys))))
    return losses, net


@pytest.mark.slow
def test_dp_loss_parity_with_single_device():
    def loss_fn(model, x, y):
        return F.cross_entropy(model(x), y)

    # single-device compiled step
    losses_single, _ = _run_training(
        lambda net, opt: paddle.jit.TrainStep(net, loss_fn, opt))

    # 8-way DP via fleet hybrid engine
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)

    def build(net, opt):
        dm = fleet.distributed_model(net)
        return dm.compile_train_step(loss_fn, opt)

    losses_dp, _ = _run_training(build)
    np.testing.assert_allclose(losses_single, losses_dp, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_tp_gspmd_loss_parity():
    from paddle_tpu.distributed.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear)

    def loss_fn(model, x, y):
        return F.cross_entropy(model(x), y)

    class TPMLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = ColumnParallelLinear(8, 32, gather_output=False)
            self.fc2 = RowParallelLinear(32, 4, input_is_parallel=True)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(21)
    net = TPMLP()
    init_sd = {k: paddle.to_tensor(v.numpy()) for k, v in net.state_dict().items()}
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
    dm = fleet.distributed_model(net)
    step = dm.compile_train_step(loss_fn, opt)
    rng = np.random.RandomState(5)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = rng.randint(0, 4, size=(16,)).astype(np.int64)
    tp_losses = [float(step(paddle.to_tensor(xs), paddle.to_tensor(ys)))
                 for _ in range(4)]

    # reference: plain Linear seeded with the TP model's INITIAL weights
    pmesh.set_global_mesh(None)
    dist.topology.set_hybrid_communicate_group(None)
    ref = MLPWithSameInit()
    ref.set_state_dict(init_sd)
    opt2 = paddle.optimizer.AdamW(learning_rate=0.01, parameters=ref.parameters())
    sstep = paddle.jit.TrainStep(ref, loss_fn, opt2)
    ref_losses = [float(sstep(paddle.to_tensor(xs), paddle.to_tensor(ys)))
                  for _ in range(4)]
    np.testing.assert_allclose(tp_losses, ref_losses, rtol=2e-4, atol=1e-5)


class MLPWithSameInit(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


@pytest.mark.slow
def test_zero_stage1_opt_state_sharded():
    def loss_fn(model, x, y):
        return F.mse_loss(model(x), y)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "sharding_degree": 8}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 1}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(3)
    net = MLP()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
    dm = fleet.distributed_model(net)
    step = dm.compile_train_step(loss_fn, opt)
    x = paddle.randn([16, 8])
    y = paddle.randn([16, 4])
    step(x, y)
    # moment1 of fc1.weight (shape [8, 32]) should be sharded over 'sharding'
    m1 = opt._accumulators[id(net.fc1.weight)]["moment1"]
    shardings = {tuple(d.device.id for d in m1.addressable_shards)}
    assert len(m1.addressable_shards) == 8
    shard_shape = m1.addressable_shards[0].data.shape
    assert shard_shape == (1, 32), shard_shape


def test_manual_mp_layers_inside_shard_map():
    """Manual-mode TP layers: run a column+row pair under shard_map and
    compare with the dense computation."""
    from paddle_tpu.distributed.meta_parallel import mp_layers as mpl

    mesh = pmesh.build_mesh({"mp": 8})
    pmesh.set_global_mesh(mesh)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 16).astype(np.float32)
    w1 = rng.randn(16, 32).astype(np.float32)
    w2 = rng.randn(32, 8).astype(np.float32)

    def fn(xv, w1v, w2v):
        with pcontext.manual_parallel({"mp": "mp"}):
            h = jnp.maximum(jnp.matmul(xv, w1v), 0)
            y = jnp.matmul(h, w2v)
            y = lax.psum(y, "mp")
        return y

    f = jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P(), P(None, "mp"), P("mp", None)),
        out_specs=P(), check_vma=False))
    out = np.asarray(f(x, w1, w2))
    ref = np.maximum(x @ w1, 0) @ w2
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_pipeline_spmd_matches_serial():
    """Fill-drain pipeline over 8 pp stages == serial application."""
    mesh = pmesh.build_mesh({"pp": 8})
    rng = np.random.RandomState(1)
    M, mbs, h = 4, 2, 16
    x = rng.randn(M, mbs, h).astype(np.float32)
    # stage params: one matrix per stage, stacked (8, h, h)
    ws = (rng.randn(8, h, h) * 0.1).astype(np.float32)

    def stage_fn(w, a):
        return jnp.tanh(a @ w)

    def pp_fn(w_local, mb):
        out = ppipe.pipeline_spmd(lambda wp, a: stage_fn(wp[0], a), w_local, mb,
                                  axis_name="pp")
        return ppipe.last_stage_broadcast(out, "pp")

    f = jax.jit(shard_map(pp_fn, mesh=mesh,
                              in_specs=(P("pp"), P()), out_specs=P(),
                              check_vma=False))
    out = np.asarray(f(ws, x))
    # serial reference
    ref = x.copy()
    for s in range(8):
        ref = np.tanh(ref @ ws[s])
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_pipeline_spmd_gradients():
    mesh = pmesh.build_mesh({"pp": 4})
    rng = np.random.RandomState(2)
    M, mbs, h = 4, 2, 8
    x = rng.randn(M, mbs, h).astype(np.float32)
    ws = (rng.randn(4, h, h) * 0.1).astype(np.float32)

    def loss_fn(w, xin):
        def pp_fn(w_local, mb):
            out = ppipe.pipeline_spmd(lambda wp, a: jnp.tanh(a @ wp[0]),
                                      w_local, mb, axis_name="pp")
            out = ppipe.last_stage_broadcast(out, "pp")
            # replicated loss
            return jnp.sum(out ** 2)
        f = shard_map(pp_fn, mesh=mesh, in_specs=(P("pp"), P()),
                          out_specs=P(), check_vma=False)
        return f(w, xin)

    g = jax.jit(jax.grad(loss_fn))(ws, x)

    def serial_loss(w, xin):
        out = xin
        for s in range(4):
            out = jnp.tanh(out @ w[s])
        return jnp.sum(out ** 2)

    g_ref = jax.jit(jax.grad(serial_loss))(ws, x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-3,
                               atol=1e-4)


def test_shard_tensor_api():
    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    t = dist.shard_tensor(np.ones((8, 4), np.float32), mesh,
                          [dist.Shard(0), dist.Replicate()])
    assert t.is_distributed
    assert t._sharding_spec == P("x", None)
