"""Functional comm API parity: p2p mailbox, batch_isend_irecv, stream
namespace (SURVEY.md §2.3 Python comm API row)."""

import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.communication import p2p, stream


def test_send_recv_roundtrip():
    src_val = np.arange(6, dtype=np.float32).reshape(2, 3)
    t = paddle.to_tensor(src_val)
    out = paddle.to_tensor(np.zeros((2, 3), np.float32))
    dist.send(t, dst=0)
    task = dist.recv(out, src=0)
    assert task.wait()
    np.testing.assert_array_equal(np.asarray(out._value), src_val)


def test_recv_timeout():
    out = paddle.to_tensor(np.zeros((1,), np.float32))
    with pytest.raises(TimeoutError):
        p2p._mailbox.take((42, 0, 7), timeout=0.1)


def test_isend_irecv_tasks():
    t = paddle.to_tensor(np.ones((4,), np.float32) * 3)
    out = paddle.to_tensor(np.zeros((4,), np.float32))
    st = dist.isend(t, dst=0, tag=1)
    rt = dist.irecv(out, src=0, tag=1)
    assert st.is_completed() and rt.is_completed()
    np.testing.assert_array_equal(np.asarray(out._value), 3 * np.ones(4))


def test_batch_isend_irecv_ordering():
    """Sends post before receives regardless of list order (GroupStart/End
    guarantee) — a recv listed before its matching send must not deadlock."""
    a = paddle.to_tensor(np.full((2,), 5, np.float32))
    b = paddle.to_tensor(np.full((2,), 7, np.float32))
    ra = paddle.to_tensor(np.zeros((2,), np.float32))
    rb = paddle.to_tensor(np.zeros((2,), np.float32))
    ops = [
        dist.P2POp(dist.irecv, ra, peer=0, tag=10),
        dist.P2POp(dist.isend, a, peer=0, tag=10),
        dist.P2POp(dist.irecv, rb, peer=0, tag=11),
        dist.P2POp(dist.isend, b, peer=0, tag=11),
    ]
    tasks = dist.batch_isend_irecv(ops)
    assert len(tasks) == 4 and all(t.wait() for t in tasks)
    np.testing.assert_array_equal(np.asarray(ra._value), [5, 5])
    np.testing.assert_array_equal(np.asarray(rb._value), [7, 7])


def test_p2pop_validates_op():
    t = paddle.to_tensor(np.zeros((1,), np.float32))
    with pytest.raises(ValueError):
        dist.P2POp(dist.all_reduce, t, peer=0)


def test_mailbox_cross_thread():
    got = {}

    def sender():
        p2p._mailbox.put((3, 0, 0), np.float32(42.0))

    th = threading.Thread(target=sender)
    th.start()
    got["v"] = p2p._mailbox.take((3, 0, 0), timeout=5)
    th.join()
    assert float(got["v"]) == 42.0


def test_stream_namespace_delegates():
    x = paddle.to_tensor(np.ones((8, 2), np.float32))
    y = stream.all_reduce(x, use_calc_stream=True)
    assert y is x  # world size 1: identity, in-place semantics
    out = stream.all_gather(x, use_calc_stream=False)
    assert out is not None


def test_eager_pp_train_batch_rejects_multiprocess(monkeypatch):
    """VERDICT round-2 weak #8: the eager fleet PP engine must fail FAST
    under a multi-process launcher, naming the compiled route."""
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.meta_parallel.pipeline_parallel import \
        PipelineParallel
    from paddle_tpu.distributed.meta_parallel.pp_layers import (
        LayerDesc, PipelineLayer)

    paddle.seed(0)
    layers = PipelineLayer(
        layers=[LayerDesc(nn.Linear, 4, 4) for _ in range(2)],
        num_stages=1, loss_fn=lambda out, lab: (out - lab).square().mean())
    pp = PipelineParallel(layers, None, None)
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
    opt = optimizer.SGD(learning_rate=0.1, parameters=layers.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    with pytest.raises(RuntimeError, match="build_hybrid_train_step"):
        pp.train_batch((x, x), opt)
