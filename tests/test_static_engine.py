"""static.Executor / load_inference_model and auto_parallel.Engine
(SURVEY.md §2.1 executor row, §2.4 auto-parallel row)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static
from paddle_tpu.static import InputSpec
from paddle_tpu.distributed.auto_parallel import Engine, shard_layer
from paddle_tpu.distributed import ProcessMesh, Shard, Replicate


def _net():
    paddle.seed(11)
    return nn.Sequential(nn.Linear(6, 24), nn.Tanh(), nn.Linear(24, 2))


def test_executor_runs_loaded_program(tmp_path):
    net = _net()
    x = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    ref = np.asarray(net(paddle.to_tensor(x))._value)
    prefix = str(tmp_path / "prog")
    static.save_inference_model(prefix, [InputSpec([3, 6], "float32")],
                                None, layer=net)
    exe = static.Executor()
    prog, feed_names, _ = static.load_inference_model(prefix, exe)
    outs = exe.run(prog, feed={feed_names[0]: x}, fetch_list=[0])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5)


def test_executor_missing_feed_raises(tmp_path):
    net = _net()
    prefix = str(tmp_path / "prog2")
    static.save_inference_model(prefix, [InputSpec([1, 6], "float32")],
                                None, layer=net)
    exe = static.Executor()
    prog, _, _ = static.load_inference_model(prefix, exe)
    with pytest.raises(ValueError):
        exe.run(prog, feed={}, fetch_list=[0])


def test_engine_fit_evaluate_predict():
    from paddle_tpu.io import TensorDataset
    net = _net()
    rng = np.random.RandomState(1)
    X = rng.randn(32, 6).astype(np.float32)
    W = rng.randn(6, 2).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    ds = TensorDataset([X, Y])

    def mse(pred, label):
        return ((pred - label) ** 2).mean()

    eng = Engine(net, loss=mse,
                 optimizer=optimizer.AdamW(learning_rate=2e-2,
                                           parameters=net.parameters()))
    hist = eng.fit(ds, epochs=6, batch_size=8)
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.5, hist
    ev = eng.evaluate(ds, batch_size=8)
    assert ev["loss"] == pytest.approx(hist[-1]["loss"], rel=0.8)
    preds = eng.predict(ds, batch_size=8)
    assert len(preds) == 4 and preds[0].shape == (8, 2)


def test_shard_layer_places_params():
    net = _net()
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])

    def shard_fn(name, layer, pmesh):
        if "weight" in name and "0" in name:
            return [Replicate(), Shard(1)]  # shard out-features over 'y'
        return None

    shard_layer(net, mesh, shard_fn)
    w = net[0].weight
    assert w._sharding_spec is not None
    # 24 out-features over y=4 -> shard dim 1 in 4 pieces
    shards = w._value.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape == (6, 6)
