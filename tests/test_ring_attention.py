"""Ring attention parity tests on the 8-device CPU mesh: blockwise ring
result must equal full-sequence attention (SURVEY.md §5.7)."""

import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from paddle_tpu.core.compat import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.ops import flash_attention as fa
from paddle_tpu.ops import ring_attention as ra
from paddle_tpu.parallel import mesh as pmesh

pytestmark = pytest.mark.slow  # core tier: -m 'not slow'


@pytest.fixture(autouse=True)
def reset_mesh():
    pmesh.set_global_mesh(None)
    yield
    pmesh.set_global_mesh(None)


def _qkv(b=2, s=32, h=4, hkv=None, d=16, seed=0):
    rng = np.random.RandomState(seed)
    hkv = hkv or h
    q = rng.randn(b, s, h, d).astype(np.float32)
    k = rng.randn(b, s, hkv, d).astype(np.float32)
    v = rng.randn(b, s, hkv, d).astype(np.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sep", [2, 4, 8])
def test_ring_matches_full(causal, sep):
    mesh = pmesh.build_mesh({"sep": sep})
    q, k, v = _qkv()
    scale = 1.0 / math.sqrt(q.shape[-1])
    want = fa._sdpa_array(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          scale=scale, causal=causal)
    prog = shard_map(
        lambda a, b_, c: ra.ring_attention_array(a, b_, c, "sep",
                                                 causal=causal),
        mesh=mesh, in_specs=(P(None, "sep"),) * 3, out_specs=P(None, "sep"),
        check_vma=False)
    got = prog(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gqa():
    mesh = pmesh.build_mesh({"sep": 4})
    q, k, v = _qkv(h=8, hkv=2)
    scale = 1.0 / math.sqrt(q.shape[-1])
    want = fa._sdpa_array(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          scale=scale, causal=True)
    prog = shard_map(
        lambda a, b_, c: ra.ring_attention_array(a, b_, c, "sep"),
        mesh=mesh, in_specs=(P(None, "sep"),) * 3, out_specs=P(None, "sep"),
        check_vma=False)
    got = prog(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_grad_matches_full():
    mesh = pmesh.build_mesh({"sep": 4})
    pmesh.set_global_mesh(mesh)
    q, k, v = _qkv(s=16)
    scale = 1.0 / math.sqrt(q.shape[-1])

    def loss_full(a, b_, c):
        return jnp.sum(fa._sdpa_array(a, b_, c, scale=scale, causal=True) ** 2)

    def loss_ring(a, b_, c):
        prog = shard_map(
            lambda x, y, z: ra.ring_attention_array(x, y, z, "sep"),
            mesh=mesh, in_specs=(P(None, "sep"),) * 3,
            out_specs=P(None, "sep"), check_vma=False)
        return jnp.sum(prog(a, b_, c) ** 2)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b_ in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=5e-5, atol=5e-5)


def test_ring_flash_attention_eager_api():
    mesh = pmesh.build_mesh({"sep": 4})
    pmesh.set_global_mesh(mesh)
    q, k, v = _qkv()
    qt, kt, vt = (paddle.to_tensor(t) for t in (q, k, v))
    qt.stop_gradient = False
    out = ra.ring_flash_attention(qt, kt, vt, causal=True)
    want = fa._sdpa_array(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          scale=1.0 / math.sqrt(16), causal=True)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    out.backward()
    assert qt._grad_value is not None


def test_ring_sep4_nontoy_parity_fwd_bwd():
    """VERDICT r4 next-round #2: sep=4 at NON-TOY S_local (1024 per device,
    S=4096 global) — ring fwd AND bwd must match global attention."""
    mesh = pmesh.build_mesh({"sep": 4})
    pmesh.set_global_mesh(mesh)
    rng = np.random.RandomState(11)
    b, s, h, d = 1, 4096, 2, 64
    q = rng.randn(b, s, h, d).astype(np.float32) * 0.5
    k = rng.randn(b, s, h, d).astype(np.float32) * 0.5
    v = rng.randn(b, s, h, d).astype(np.float32) * 0.5
    scale = 1.0 / math.sqrt(d)

    want = fa._sdpa_array(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          scale=scale, causal=True)
    prog = shard_map(
        lambda a, b_, c: ra.ring_attention_array(a, b_, c, "sep",
                                                 causal=True),
        mesh=mesh, in_specs=(P(None, "sep"),) * 3, out_specs=P(None, "sep"),
        check_vma=False)
    got = jax.jit(prog)(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    def loss_ring(a, b_, c):
        return jnp.sum(prog(a, b_, c) ** 2) / s

    def loss_full(a, b_, c):
        return jnp.sum(
            fa._sdpa_array(a, b_, c, scale=scale, causal=True) ** 2) / s

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b_ in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=5e-4, atol=5e-4)


def test_llama_ring_sep_mode_loss_matches_ulysses():
    """Full hybrid train step with sep>1: ring and ulysses modes give the
    same first-step loss (same math, different comm pattern)."""
    from paddle_tpu.models import llama as L
    losses = {}
    for mode in ("ulysses", "ring"):
        mesh = pmesh.build_mesh({"sep": 4, "mp": 2})
        pmesh.set_global_mesh(mesh)
        cfg = L.llama_tiny(num_hidden_layers=2)
        cfg.sep_mode = mode
        step, init_fn = L.build_hybrid_train_step(cfg, mesh,
                                                  learning_rate=1e-3)
        params, opt_state = init_fn(seed=0)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (1, 2, 32)).astype(np.int32)
        labels = np.roll(ids, -1, axis=-1).astype(np.int32)
        loss, params, opt_state = step(params, opt_state, ids, labels)
        losses[mode] = float(loss)
        pmesh.set_global_mesh(None)
    assert np.isfinite(losses["ring"])
    np.testing.assert_allclose(losses["ring"], losses["ulysses"], rtol=1e-4)


@pytest.mark.slow
def test_kernel_kv_rep_gqa_interpret():
    """GQA through the ACTUAL Pallas kernels via kv_rep index maps
    (interpret mode): parity vs materialized-repeat reference, fwd + bwd."""
    import jax.numpy as jnp
    from paddle_tpu.ops import flash_attention as fa

    rng = np.random.RandomState(11)
    B, HQ, HK, S, D = 2, 4, 2, 256, 128
    rep = HQ // HK
    bq = bk = 128
    q = jnp.asarray(rng.randn(B * HQ, S, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B * HK, S, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B * HK, S, D).astype(np.float32))
    sc = 1.0 / np.sqrt(D)

    out, lse = fa._flash_fwd_pallas(q, k, v, sc, True, bq, bk, kv_rep=rep,
                                    interpret=True)
    # reference: repeat KV heads explicitly
    k_rep = jnp.repeat(k.reshape(B, HK, S, D), rep, axis=1).reshape(
        B * HQ, S, D)
    v_rep = jnp.repeat(v.reshape(B, HK, S, D), rep, axis=1).reshape(
        B * HQ, S, D)
    ref = fa._attn_ref(q, k_rep, v_rep, sc, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    g = jnp.asarray(rng.randn(B * HQ, S, D).astype(np.float32))
    dq, dk, dv = fa._flash_bwd_pallas(q, k, v, out, lse, g, sc, True,
                                      bq, bk, kv_rep=rep, interpret=True)
    _, vjp = jax.vjp(lambda a, b_, c: fa._attn_ref(
        a,
        jnp.repeat(b_.reshape(B, HK, S, D), rep, axis=1).reshape(B * HQ, S, D),
        jnp.repeat(c.reshape(B, HK, S, D), rep, axis=1).reshape(B * HQ, S, D),
        sc, True), q, k, v)
    rdq, rdk, rdv = vjp(g)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(rdq),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(rdk),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(rdv),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_ring_block_bwd_matches_autodiff_of_block_fwd():
    """The hand-written global-softmax block backward (_block_bwd ref path)
    must equal autodiff through the merged two-block forward."""
    import jax.numpy as jnp
    from paddle_tpu.ops import ring_attention as ra

    rng = np.random.RandomState(12)
    BH, S, D = 4, 32, 16
    rep = 2
    q = jnp.asarray(rng.randn(BH, S, D).astype(np.float32))
    k1 = jnp.asarray(rng.randn(BH // rep, S, D).astype(np.float32))
    v1 = jnp.asarray(rng.randn(BH // rep, S, D).astype(np.float32))
    k2 = jnp.asarray(rng.randn(BH // rep, S, D).astype(np.float32))
    v2 = jnp.asarray(rng.randn(BH // rep, S, D).astype(np.float32))
    sc = 1.0 / np.sqrt(D)

    def merged(qq, ka, va, kb, vb):
        o1, l1 = ra._block_ref(qq, ka, va, sc, False, rep)
        o2, l2 = ra._block_ref(qq, kb, vb, sc, False, rep)
        o, _ = ra._merge(o1, l1, o2, l2)
        return o

    out = merged(q, k1, v1, k2, v2)
    # global lse of the two blocks
    _, l1 = ra._block_ref(q, k1, v1, sc, False, rep)
    _, l2 = ra._block_ref(q, k2, v2, sc, False, rep)
    lse = jnp.logaddexp(l1, l2)
    g = jnp.asarray(rng.randn(BH, S, D).astype(np.float32))

    dq1, dk1, dv1 = ra._block_bwd(q, k1, v1, out, lse, g, sc, False, rep)
    dq2, dk2, dv2 = ra._block_bwd(q, k2, v2, out, lse, g, sc, False, rep)

    _, vjp = jax.vjp(merged, q, k1, v1, k2, v2)
    rdq, rdk1, rdv1, rdk2, rdv2 = vjp(g)
    np.testing.assert_allclose(np.asarray(dq1 + dq2), np.asarray(rdq),
                               rtol=2e-3, atol=2e-3)
    for got, want in [(dk1, rdk1), (dv1, rdv1), (dk2, rdk2), (dv2, rdv2)]:
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)
