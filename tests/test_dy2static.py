"""SOT-analog control-flow conversion + dynamic-shape bucketing
(VERDICT round-2 item 6).

Reference: python/paddle/jit/sot/ + python/paddle/jit/dy2static/ — the
conversion of data-dependent Python if/while over Tensors into compiled
cond/while ops, and the bucketing policy for ragged shapes (SURVEY.md §2.5
dy2static + CINN rows).
"""

import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.jit.dy2static import (ConversionError,
                                      convert_control_flow)


class TestIfConversion:
    def test_data_dependent_if_compiles_once_and_matches_eager(self):
        def f(x):
            if (x.sum() > 0):
                y = x * 2.0
            else:
                y = x - 1.0
            return y + 1.0

        sf = jit.to_static(f)
        pos = paddle.to_tensor(np.ones((3,), np.float32))
        neg = paddle.to_tensor(-np.ones((3,), np.float32))
        # eager reference
        np.testing.assert_allclose(np.asarray(sf(pos)._value),
                                   np.asarray(f(pos)._value))
        np.testing.assert_allclose(np.asarray(sf(neg)._value),
                                   np.asarray(f(neg)._value))
        # same shape signature -> ONE compile even though the branch flips
        assert sf.recompile_count == 0

    def test_both_branch_return_form(self):
        def f(x):
            if (x.mean() > 0):
                return x * 3.0
            else:
                return -x

        sf = jit.to_static(f)
        pos = paddle.to_tensor(np.ones((4,), np.float32))
        neg = paddle.to_tensor(-np.ones((4,), np.float32))
        np.testing.assert_allclose(np.asarray(sf(pos)._value), 3.0)
        np.testing.assert_allclose(np.asarray(sf(neg)._value), 1.0)

    def test_python_bool_condition_untouched(self):
        calls = []

        def f(x, flag=True):
            if flag:
                calls.append("t")
                y = x + 1
            else:
                calls.append("f")
                y = x - 1
            return y

        sf = jit.to_static(f)
        out = sf(paddle.to_tensor(np.zeros((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out._value), 1.0)
        # concrete predicate executes only the taken branch
        assert calls == ["t"]

    def test_single_branch_assignment_diagnostic(self):
        def f(x):
            if (x.sum() > 0):
                y = x * 2.0
            return y  # noqa: F821 — y undefined when branch not taken

        # strict mode keeps the round-3 actionable raise
        paddle.set_flags({"FLAGS_dy2static_fallback": 0})
        try:
            sf = jit.to_static(f)
            with pytest.raises(ConversionError, match="initialise"):
                sf(paddle.to_tensor(np.ones((2,), np.float32)))
        finally:
            paddle.set_flags({"FLAGS_dy2static_fallback": 1})
        # default mode (r5): falls back to eager and produces the value
        sf2 = jit.to_static(f)
        with pytest.warns(UserWarning, match="EAGER"):
            out = sf2(paddle.to_tensor(np.ones((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out._value), 2.0)

    def test_unconvertible_return_pattern_diagnostic(self):
        def f(x):
            if (x.sum() > 0):
                return x
            x = x + 1
            return x

        paddle.set_flags({"FLAGS_dy2static_fallback": 0})
        try:
            sf = jit.to_static(f)
            with pytest.raises(ConversionError, match="single return"):
                sf(paddle.to_tensor(np.ones((2,), np.float32)))
        finally:
            paddle.set_flags({"FLAGS_dy2static_fallback": 1})
        sf2 = jit.to_static(f)
        with pytest.warns(UserWarning, match="EAGER"):
            out = sf2(paddle.to_tensor(np.ones((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out._value), 1.0)

    def test_one_armed_concrete_if_preserves_name_semantics(self):
        """A variable assigned only under a concrete-False `if` must stay
        unbound (original Python behaviour), not leak a placeholder."""
        def f(x, flag=False):
            if flag:
                y = x + 1
            return x

        out = convert_control_flow(f)(
            paddle.to_tensor(np.ones((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out._value), 1.0)

        def g(x, flag=False):
            if flag:
                y = x + 1
            return y  # unbound when flag is False

        with pytest.raises(NameError):
            convert_control_flow(g)(
                paddle.to_tensor(np.ones((2,), np.float32)))

    def test_elif_chain_traced(self):
        def f(x):
            if (x.sum() > 10):
                y = x * 2.0
            elif (x.sum() > 0):
                y = x + 1.0
            else:
                y = -x
            return y

        sf = jit.to_static(f)
        big = paddle.to_tensor(np.full((4,), 9.0, np.float32))   # sum 36
        mid = paddle.to_tensor(np.full((4,), 0.5, np.float32))   # sum 2
        neg = paddle.to_tensor(np.full((4,), -1.0, np.float32))
        np.testing.assert_allclose(np.asarray(sf(big)._value), 18.0)
        np.testing.assert_allclose(np.asarray(sf(mid)._value), 1.5)
        np.testing.assert_allclose(np.asarray(sf(neg)._value), 1.0)
        assert sf.recompile_count == 0

    def test_if_nested_inside_while(self):
        """An assigning `if` inside a converted `while` must not confuse
        the while's return/break detection (nested-def pruning)."""
        def f(x):
            s = x * 0.0
            while (s.sum() < 6.0):
                if (x.sum() > 0):
                    s = s + x
                else:
                    s = s + 1.0
            return s

        sf = jit.to_static(f)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(np.asarray(sf(x)._value),
                                   np.asarray(f(x)._value))

    def test_closure_variables_preserved(self):
        scale = 5.0

        def f(x):
            if (x.sum() > 0):
                y = x * scale
            else:
                y = x / scale
            return y

        sf = jit.to_static(f)
        out = sf(paddle.to_tensor(np.ones((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out._value), 5.0)


class TestWhileConversion:
    def test_data_dependent_while_matches_eager(self):
        def f(x):
            s = x * 0.0
            while (s.sum() < 10.0):
                s = s + x
            return s

        sf = jit.to_static(f)
        x = paddle.to_tensor(np.ones((4,), np.float32))
        out = sf(x)
        ref = f(x)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value))
        assert sf.recompile_count == 0

    def test_while_with_break_converts(self):
        """Round 4: break inside a data-dependent while lowers via flag
        lowering instead of raising (the round-3 diagnostic is gone)."""
        def f(x):
            s = x * 0.0
            while (s.sum() < 10.0):
                s = s + x
                if (s.sum() >= 6.0):
                    break
            return s

        sf = jit.to_static(f)
        out = np.asarray(sf(paddle.to_tensor(
            np.ones((4,), np.float32)))._value)
        # 4 per iteration; breaks once the sum reaches >= 6 (two rounds)
        np.testing.assert_allclose(out, 2.0 * np.ones(4))

    def test_while_with_return_converts(self):
        """round-5: return inside a data-dependent while CONVERTS via the
        single-exit flag lowering (was a diagnostic raise through r4)."""
        def f(x):
            s = x * 0.0
            while (s.sum() < 10.0):
                s = s + x
                if (s.sum() > 6.0):
                    return s * -1.0
            return s

        sf = jit.to_static(f)
        with warnings.catch_warnings():
            warnings.simplefilter("error")   # no fallback warning allowed
            out = sf(paddle.to_tensor(np.ones((4,), np.float32)))
        np.testing.assert_allclose(np.asarray(out._value), -2.0 * np.ones(4))

    def test_concrete_while_unchanged(self):
        def f(x, n=3):
            i = 0
            while i < n:
                x = x + 1.0
                i += 1
            return x

        # concrete trip count: runs as plain Python (i stays an int)
        out = convert_control_flow(f)(
            paddle.to_tensor(np.zeros((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out._value), 3.0)


class TestLayerIntegration:
    def test_layer_forward_with_tensor_branch(self):
        class Gate(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)

            def forward(self, x):
                h = self.lin(x)
                if (h.sum() > 0):
                    out = h * 2.0
                else:
                    out = h * 0.5
                return out

        paddle.seed(0)
        net = Gate()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        eager = net(x)
        jit.to_static(net)                       # converts forward in place
        static = net(x)
        np.testing.assert_allclose(np.asarray(static._value),
                                   np.asarray(eager._value), rtol=1e-6)


class TestBucketing:
    def test_next_bucket_and_pad(self):
        assert jit.next_bucket(87, (64, 128, 256)) == 128
        assert jit.next_bucket(100) == 128  # multiple=64 rounding
        with pytest.raises(ValueError, match="largest bucket"):
            jit.next_bucket(300, (64, 128, 256))
        x = paddle.to_tensor(np.ones((87, 4), np.float32))
        padded, n = jit.pad_to_bucket(x, axis=0, buckets=(64, 128))
        assert tuple(padded.shape) == (128, 4) and n == 87
        np.testing.assert_allclose(np.asarray(padded._value)[87:], 0.0)

    def test_bucketer_bounds_signatures(self):
        bucketer = jit.ShapeBucketer(axes={0: (64, 128)})
        for n in (10, 30, 60, 70, 100, 128):
            _, valid = bucketer(paddle.to_tensor(
                np.ones((n, 2), np.float32)))
            assert valid[0] == n
        assert bucketer.num_signatures == 2      # only the two buckets

    def test_bucketer_keeps_compile_guard_quiet(self):
        """Ragged batch sizes through a compiled fn: bucketed inputs give
        at most one recompile (two buckets), instead of one per shape."""
        def f(x):
            return (x * 2.0).sum(axis=1)

        sf = jit.to_static(f)
        bucketer = jit.ShapeBucketer(axes={0: (32, 64)})
        for n in (5, 17, 29, 40, 55, 64):
            padded, valid = bucketer(paddle.to_tensor(
                np.ones((n, 3), np.float32)))
            out = sf(padded)
            assert out.shape[0] in (32, 64)
        assert sf.recompile_count <= 1
