"""HBM memory ledger (paddle_tpu.observability.memory): byte-level
accounting by class, the capacity planner validated against real pools,
per-request attribution, byte conservation across storm / speculative /
chaos serving, and OOM forensics (oom_pressure events + memory.json
flight bundles + /memz)."""

import json
import tarfile
import urllib.request

import numpy as np
import pytest

from paddle_tpu.kvcache import RefcountedKVCacheManager
from paddle_tpu.observability import get_registry
from paddle_tpu.observability.events import configure_event_log
from paddle_tpu.observability.flight import flight_recorder
from paddle_tpu.observability.memory import (MemoryLedger, memory_armed,
                                             memory_ledger, page_nbytes,
                                             plan_capacity, plan_verdict,
                                             pool_occupancy,
                                             pytree_nbytes)
from paddle_tpu.ops.paged_attention import PagedKVCacheManager


@pytest.fixture()
def mem():
    """Armed, clean process-global ledger; disarmed + reset afterwards
    (and the flight recorder left disarmed) so no other test inherits
    memory-plane state."""
    memory_ledger.reset()
    memory_ledger.arm()
    yield memory_ledger
    memory_ledger.disarm()
    memory_ledger.reset()
    flight_recorder.disarm()
    flight_recorder.clear()


def _mgr(num_pages=12, page_size=4, layers=1, heads=1, dim=2):
    return RefcountedKVCacheManager(layers, num_pages, page_size, heads,
                                    dim)


# ---------------------------------------------------------------------------
# planner + pure helpers
# ---------------------------------------------------------------------------

def test_page_nbytes_is_geometry_derived_and_dtype_aware():
    # 2 (K+V) x layers x page x heads x dim x itemsize
    assert page_nbytes(2, 4, 1, 2, 4) == 2 * 2 * 4 * 1 * 2 * 4
    # int8 pages halve the bf16 cost with no other change
    assert page_nbytes(2, 4, 1, 2, 1) * 2 == page_nbytes(2, 4, 1, 2, 2)


def test_planner_prediction_matches_real_pool_on_two_geometries():
    """Acceptance bar: max-page prediction matches the live pool's
    capacity EXACTLY, on two different geometries (dtype, heads, page
    size all varied)."""
    import jax.numpy as jnp
    for mgr in (
        PagedKVCacheManager(2, 10, 4, 1, 2),                  # bf16
        RefcountedKVCacheManager(3, 33, 8, 2, 4,
                                 dtype=jnp.float32),          # fp32
    ):
        shape = mgr.k_pages.shape
        plan = plan_capacity(
            num_layers=shape[0], num_kv_heads=shape[3],
            head_dim=shape[4], page_size=shape[2],
            dtype_bytes=mgr.k_pages.dtype.itemsize,
            hbm_bytes=int(mgr.k_pages.nbytes) + int(mgr.v_pages.nbytes))
        v = plan_verdict(plan, mgr)
        assert v["exact"], v
        assert plan.page_bytes == mgr.page_nbytes
        assert plan.max_pages == mgr.usable_pages


def test_planner_slots_and_context_math():
    plan = plan_capacity(num_layers=2, num_kv_heads=1, head_dim=2,
                         page_size=4, dtype_bytes=2, hbm_bytes=100_000,
                         weight_bytes=36_000, max_seq_len=32)
    assert plan.kv_budget_bytes == 64_000
    assert plan.page_bytes == 2 * 2 * 4 * 1 * 2 * 2      # 64
    assert plan.total_pages == 1000 and plan.max_pages == 999
    assert plan.pages_per_seq == 8 and plan.max_slots == 124
    assert plan.max_context_tokens == 999 * 4


def test_pytree_nbytes_matches_llama_analytic_param_bytes():
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=0)
    assert pytree_nbytes(params) == L.param_nbytes(cfg)
    assert L.param_count(cfg) > 0


def test_pool_occupancy_is_the_shared_derivation():
    mgr = _mgr(num_pages=8, page_size=4)
    t = mgr.allocate("a", 8)
    for p in t:
        mgr.adopt_cached(p)
    mgr.free("a")                      # 2 cached, 5 free, 0 live
    occ = pool_occupancy(mgr)
    assert occ == {"usable": 7, "free": 5, "live": 0, "cached": 2,
                   "pressure": pytest.approx(2 / 7),
                   "live_utilization": 0.0,
                   "cached_utilization": pytest.approx(2 / 7)}
    # exclusive pools report owned pages as live
    base = PagedKVCacheManager(1, 8, 4, 1, 2)
    base.allocate("a", 8)
    occ = pool_occupancy(base)
    assert occ["live"] == 2 and occ["cached"] == 0


# ---------------------------------------------------------------------------
# ledger accounting + byte conservation
# ---------------------------------------------------------------------------

def test_observe_splits_bytes_and_tracks_peaks(mem):
    mgr = _mgr(num_pages=12, page_size=4)
    pb = mgr.page_nbytes
    t = mgr.allocate("a", 8)
    split = mem.observe(mgr)
    assert split == {"kv_free": 9, "kv_live": 2, "kv_spec": 0,
                     "kv_cached": 0}
    assert mem.class_bytes("kv_live") == 2 * pb
    for p in t:
        mgr.adopt_cached(p)
    mgr.free("a")
    mem.observe(mgr)
    assert mem.class_bytes("kv_live") == 0
    assert mem.class_bytes("kv_cached") == 2 * pb
    assert mem.peak_bytes("kv_live") == 2 * pb     # watermark survives
    snap = mem.snapshot()
    assert snap["pools"][0]["planner"]["exact"]
    assert snap["audits"] >= 2


def test_speculative_tail_pages_are_their_own_class(mem):
    mgr = _mgr(num_pages=12, page_size=4)
    mgr.allocate("s", 4)                       # 1 reserved page
    mgr.grow_to("s", 11)                       # +2 speculative tail pages
    split = mem.observe(mgr, reserved={"s": 1})
    assert split["kv_spec"] == 2 and split["kv_live"] == 1
    mgr.truncate_pages("s", 1)                 # rejection rollback
    split = mem.observe(mgr, reserved={"s": 1})
    assert split["kv_spec"] == 0 and split["kv_free"] == 10


def test_byte_conservation_audit_detects_corruption(mem):
    mgr = _mgr(num_pages=8, page_size=4)
    mgr.allocate("a", 8)
    mem.observe(mgr)
    # a page on the free list that the radix also "caches" double-counts
    mgr._cached.add(mgr.num_pages - 3)
    with pytest.raises(RuntimeError, match="byte conservation"):
        mem.observe(mgr)


def test_weights_cached_by_identity_and_summed_across_models(mem):
    a = {"w": np.zeros((4, 4), np.float32)}
    b = {"w": np.zeros((2, 2), np.float32)}
    assert mem.note_weights(a) == 64
    mem.note_weights(a)                        # same object: no double
    assert mem.class_bytes("weights") == 64
    mem.note_weights(b)
    assert mem.class_bytes("weights") == 64 + 16


def test_mem_gauges_in_registry_exposition(mem):
    mgr = _mgr()
    mgr.allocate("a", 4)
    mem.observe(mgr)
    text = get_registry().prometheus_text()
    assert 'paddle_mem_bytes{class="kv_live"}' in text
    assert 'paddle_mem_peak_bytes{class="kv_free"}' in text


def test_disarmed_gate_leaves_ledger_untouched(mem):
    mem.disarm()
    from paddle_tpu.models import llama as L
    from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                               GenerationConfig)
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=0)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=4), num_slots=2,
        page_size=4, max_seq_len=32, chunk=2)
    eng.serve(params, [np.arange(1, 6, dtype=np.int32)])
    assert not memory_armed[0]
    assert mem.audits == 0 and mem.snapshot()["pools"] == []


# ---------------------------------------------------------------------------
# engine integration: conservation across storm / COW / spec / chaos
# ---------------------------------------------------------------------------

def _engine(prefix_cache=False, speculative=False, num_slots=2,
            num_pages=None, max_new=6, seed=3):
    from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                               GenerationConfig)
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=seed)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=max_new, seed=seed),
        num_slots=num_slots, page_size=4, max_seq_len=32, chunk=3,
        num_pages=num_pages, prefix_cache=prefix_cache,
        speculative=speculative)
    return cfg, params, eng


def test_storm_byte_conservation_cache_on_with_cow(mem):
    """Unified storm, prefix cache on, trickle admissions, COW wave: the
    ledger audits EVERY step (alongside check_conservation) and the
    warm wave's per-request attribution shows cached bytes."""
    cfg, params, eng = _engine(prefix_cache=True, num_slots=2)
    rng = np.random.RandomState(0)
    sysp = rng.randint(1, cfg.vocab_size, (12,)).astype(np.int32)
    prompts = [np.concatenate([sysp, rng.randint(
        1, cfg.vocab_size, (int(rng.randint(2, 8)),)).astype(np.int32)])
        for _ in range(4)]
    prompts.append(prompts[0][:16])            # exactly 4 pages: COW
    for wave in range(2):
        for i, p in enumerate(prompts):
            eng.submit(p)
            eng.step(params)                   # mid-decode admissions
        while eng._live or eng._queue:
            eng.step(params)
        eng.collect()
    assert mem.audits > 10
    assert eng.cache.stats["hits"] > 0 and eng.cache.stats["cow_copies"] > 0
    snap = mem.snapshot()
    pool = snap["pools"][0]
    assert pool["planner"]["exact"]
    assert pool["cache"]["hits"] == eng.cache.stats["hits"]
    assert snap["classes"]["weights"] == pytree_nbytes(params)
    assert snap["peaks"]["kv_live"] > 0


def test_storm_warm_requests_attribute_cached_bytes(mem):
    cfg, params, eng = _engine(prefix_cache=True, num_slots=1,
                               max_new=4)
    rng = np.random.RandomState(1)
    sysp = rng.randint(1, cfg.vocab_size, (12,)).astype(np.int32)
    p = np.concatenate([sysp, rng.randint(1, cfg.vocab_size, (6,)
                                          ).astype(np.int32)])
    eng.serve(params, [p])                     # cold: populates cache
    q = np.concatenate([sysp, rng.randint(1, cfg.vocab_size, (5,)
                                          ).astype(np.int32)])
    eng.submit(q)
    eng.step(params)                           # warm admission
    reqs = mem.snapshot()["pools"][0]["requests"]
    warm = [r for r in reqs.values() if r["cached_bytes"] > 0]
    assert warm and warm[0]["fresh_bytes"] > 0
    assert warm[0]["bytes"] == warm[0]["cached_bytes"] \
        + warm[0]["fresh_bytes"]
    while eng._live or eng._queue:
        eng.step(params)
    assert mem.snapshot()["pools"][0]["requests"] == {}   # pruned


def test_speculative_storm_byte_conservation(mem):
    """Spec engine (draft grow + rollback) audits every round cache-off:
    the byte books balance through grow_to/truncate_pages cycles."""
    cfg, params, eng = _engine(speculative=True, num_slots=2, max_new=8)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, cfg.vocab_size, (int(rng.randint(4, 10)),)
                           ).astype(np.int32) for _ in range(4)]
    for p in prompts:
        eng.submit(p)
        eng.step(params)
    while eng._live or eng._queue:
        eng.step(params)
    assert mem.audits > 5
    assert eng.spec.snapshot()["drafted"] > 0
    split = mem.snapshot()["pools"][0]["pages"]
    assert split["kv_free"] == eng.mgr.usable_pages   # all retired
    assert split["kv_spec"] == 0


def test_router_chaos_byte_conservation(mem):
    """2-replica fleet with a mid-storm replica kill: every request
    completes and the surviving replicas' books balance throughout."""
    from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                               GenerationConfig)
    from paddle_tpu.models import llama as L
    from paddle_tpu.resilience import Fault, FaultInjector
    from paddle_tpu.serving import SchedulerConfig
    from paddle_tpu.serving.health import HealthConfig
    from paddle_tpu.serving.replica import ReplicaHandle
    from paddle_tpu.serving.router import FleetRouter, RouterConfig

    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=3)
    replicas = [
        ReplicaHandle(
            i,
            ContinuousBatchingEngine(
                cfg, GenerationConfig(max_new_tokens=6, seed=3),
                num_slots=2, page_size=4, max_seq_len=32, chunk=2,
                prefix_cache=True),
            config=SchedulerConfig(max_step_retries=1,
                                   retry_backoff_s=0.001),
            health_config=HealthConfig())
        for i in range(2)]
    router = FleetRouter(
        replicas, config=RouterConfig(failover_backoff_s=0.001),
        fault_injector=FaultInjector(
            schedule=[Fault("replica_die", 3, replica=0)]))
    rng = np.random.RandomState(0)
    handles = [router.submit(rng.randint(1, cfg.vocab_size, (5,)
                                         ).astype(np.int32))
               for _ in range(6)]
    steps = 0
    while router.pending:
        router.step(params)
        steps += 1
        assert steps < 10_000
    assert all(h.done for h in handles)
    assert mem.audits > 0
    # two engines -> two pools in the books, each planner-exact
    pools = mem.snapshot()["pools"]
    assert len(pools) == 2
    assert all(p["planner"]["exact"] for p in pools)


# ---------------------------------------------------------------------------
# OOM forensics: events, bundles, /memz
# ---------------------------------------------------------------------------

def test_oom_emits_event_and_memory_json_bundle(tmp_path, mem):
    """Acceptance bar: a forced pool exhaustion produces a flight bundle
    whose memory.json names the exhausting class, the per-request page
    holders and the planner verdict — and /memz serves the same
    snapshot."""
    flight_recorder.clear()
    flight_recorder.arm(dump_dir=str(tmp_path / "dumps"))
    configure_event_log(str(tmp_path / "events.jsonl"))
    try:
        cfg, params, eng = _engine(prefix_cache=True, num_slots=2,
                                   num_pages=9, max_new=4)
        rng = np.random.RandomState(4)
        eng.submit(rng.randint(1, cfg.vocab_size, (8,)).astype(np.int32))
        eng.step(params)                       # one live holder
        with pytest.raises(MemoryError):
            eng.mgr.allocate("hog", 31)        # 8 pages > 5 free
    finally:
        configure_event_log(None)
    kinds = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    oom = [e for e in kinds if e["kind"] == "oom_pressure"]
    assert oom and oom[0]["source"] == "allocate"
    assert oom[0]["bytes_short"] == \
        (oom[0]["need_pages"] - oom[0]["free_pages"]) * eng.mgr.page_nbytes
    bundles = list((tmp_path / "dumps").glob("*oom_allocate*.tar.gz"))
    assert len(bundles) == 1
    with tarfile.open(bundles[0]) as tar:
        assert "memory.json" in tar.getnames()
        doc = json.load(tar.extractfile("memory.json"))
    assert doc["last_oom"]["exhausting_class"] in (
        "kv_live", "kv_spec", "kv_cached")
    assert doc["last_oom"]["pages_short"] == 3
    pool = doc["pools"][0]
    assert pool["planner"]["exact"]
    assert pool["requests"], "per-request page holders missing"
    holder = next(iter(pool["requests"].values()))
    assert holder["pages"] > 0 and holder["bytes"] > 0
    # /memz serves the same document
    from paddle_tpu.observability import DiagServer
    srv = DiagServer()
    port = srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/memz", timeout=5) as r:
            served = json.load(r)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=5) as r:
            statusz = json.load(r)
    finally:
        srv.stop()
    assert served == json.loads(
        json.dumps(memory_ledger.snapshot(), default=str))
    assert statusz["memory"]["last_oom"]["source"] == "allocate"
    # second exhaustion with the same reason: no second bundle (rate cap)
    with pytest.raises(MemoryError):
        eng.mgr.allocate("hog2", 31)
    assert len(list((tmp_path / "dumps").glob("*oom_allocate*"))) == 1


def test_admission_reject_records_shortfall(tmp_path, mem):
    """Satellite: a request deferred for pages counts into
    paddle_mem_admission_rejects_total and emits ONE oom_pressure event
    (deduped per blocked request) carrying the byte shortfall."""
    from paddle_tpu.serving import SchedulerConfig, ServingScheduler
    configure_event_log(str(tmp_path / "events.jsonl"))
    try:
        cfg, params, eng = _engine(num_slots=2, num_pages=4, max_new=4)
        sched = ServingScheduler(eng, SchedulerConfig(max_queue_depth=8))
        rng = np.random.RandomState(5)
        c0 = get_registry().counter(
            "paddle_mem_admission_rejects_total").value()
        h = [sched.submit(rng.randint(1, cfg.vocab_size, (8,)
                                      ).astype(np.int32))
             for _ in range(2)]               # each needs 3 of 3 pages
        steps = 0
        while sched.pending:
            sched.step(params)
            steps += 1
            assert steps < 10_000
        assert all(x.done for x in h)
    finally:
        configure_event_log(None)
    rejects = get_registry().counter(
        "paddle_mem_admission_rejects_total").value() - c0
    assert rejects >= 1                       # one per blocked step
    events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    adm = [e for e in events if e["kind"] == "oom_pressure"
           and e["source"] == "admission"]
    assert len(adm) == 1                      # deduped per victim
    assert adm[0]["bytes_short"] > 0
    assert adm[0]["request_id"] == h[1].rid


def test_request_spans_carry_memory_attribution(mem):
    """The admission span (and the request envelope) carry kv_pages +
    cached/fresh bytes, visible in the /tracez span tree."""
    from paddle_tpu.observability.timeline import span_collector
    from paddle_tpu.serving import SchedulerConfig, ServingScheduler
    cfg, params, eng = _engine(num_slots=2)
    sched = ServingScheduler(eng, SchedulerConfig())
    span_collector.clear()
    span_collector.arm()
    try:
        h = sched.submit(np.arange(1, 9, dtype=np.int32))
        while sched.pending:
            sched.step(params)
    finally:
        span_collector.disarm()
    tree = span_collector.tree(h.trace_id)
    assert tree, "request tree missing"
    root = tree[0]
    pb = eng.mgr.page_nbytes
    need = eng.mgr.pages_for(8 + eng.config.max_new_tokens)
    assert root["args"]["kv_pages"] == need
    assert root["args"]["fresh_bytes"] == need * pb
    assert root["args"]["cached_bytes"] == 0
    flat, stack = [], list(tree)
    while stack:
        n = stack.pop()
        flat.append(n)
        stack.extend(n.get("children", []))
    adm = [n for n in flat if n["name"].endswith(".admission")]
    assert adm and adm[0]["args"]["kv_pages"] == need
    span_collector.clear()


def test_memory_series_ride_the_history_rings(mem):
    """SignalBus.attach_scheduler samples mem.<class>_bytes into the
    MetricHistory rings alongside the latency/queue series. (A
    prefix-cache engine feeds the ledger every step; plain engines
    decimate their feed — see _note_memory.)"""
    from paddle_tpu.serving import SchedulerConfig, ServingScheduler
    cfg, params, eng = _engine(num_slots=2, prefix_cache=True)
    sched = ServingScheduler(eng, SchedulerConfig())
    fake = [0.0]

    def clock():
        fake[0] += 1.0
        return fake[0]
    sched._clock = clock
    bus = sched.attach_signal_bus(interval_s=0.5).arm()
    try:
        sched.submit(np.arange(1, 9, dtype=np.int32))
        while sched.pending:
            sched.step(params)
    finally:
        bus.disarm()
    names = bus.history.names()
    assert "mem.kv_live_bytes" in names and "mem.weights_bytes" in names
    pts = bus.history.series("mem.kv_live_bytes")
    assert pts and max(p[1] for p in pts) > 0


def test_dead_pool_ages_out_of_class_totals(mem):
    """A garbage-collected engine's pool must stop inflating the class
    totals (and /memz) — liveness is tracked with a weakref and pruned
    on the next observe/snapshot."""
    import gc
    mgr = _mgr(num_pages=12)
    mgr.allocate("a", 8)
    mem.observe(mgr)
    assert mem.class_bytes("kv_live") > 0
    first_label = mem.snapshot()["pools"][0]["label"]
    del mgr
    gc.collect()
    mgr2 = _mgr(num_pages=6)
    mem.observe(mgr2)
    snap = mem.snapshot()
    assert len(snap["pools"]) == 1
    assert snap["pools"][0]["label"] != first_label   # labels monotonic
    assert mem.class_bytes("kv_live") == 0
    assert mem.class_bytes("kv_free") == \
        mgr2.usable_pages * mgr2.page_nbytes


def test_independent_ledger_instances_do_not_share_books():
    led = MemoryLedger()
    mgr = _mgr()
    mgr.allocate("a", 4)
    led.observe(mgr)
    assert led.class_bytes("kv_live") > 0
    assert memory_ledger.class_bytes("kv_live") == 0
