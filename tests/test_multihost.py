"""Multi-host serving (ISSUE 17): wire-framed engine hosts behind a
``HostFleetRouter`` — heartbeat health, DCN page migration, host-loss
failover, host-scoped chaos, and the cross-process liveness guard.

Local tests run both engine "processes" in-process over
``LocalTransport`` (every frame still round-trips the wire encoder) on
one fake clock, so chaos arcs are deterministic and byte-identity
assertions are exact. Two tests spawn REAL engine processes over
``PipeTransport`` and kill one mid-decode with an actual SIGKILL."""

import json
import tarfile

import numpy as np
import pytest

from paddle_tpu.inference.sampling import SamplerConfig
from paddle_tpu.models import llama as L
from paddle_tpu.observability import get_registry
from paddle_tpu.observability.events import configure_event_log
from paddle_tpu.observability.flight import flight_recorder
from paddle_tpu.observability.memory import memory_ledger
from paddle_tpu.resilience import Fault, FaultInjector
from paddle_tpu.serving import (HealthConfig, HostEndpoint, HostFault,
                                HostFleetRouter, HostHandle, HostServer,
                                LocalTransport, PipeTransport, ReplicaState,
                                RouterConfig, SchedulerConfig)
from paddle_tpu.serving.multihost import llama_tiny_host

CFG = L.llama_tiny(num_hidden_layers=2)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    def advance(self, dt):
        self.t += dt


def _local_fleet(n=2, max_new=8, health_kw=None, router_kw=None,
                 injector=None, **fkw):
    """N in-process hosts over LocalTransport, one fake clock."""
    fkw.setdefault("max_new_tokens", max_new)
    fkw.setdefault("max_seq_len", 48)
    # the child scheduler enforces defer_s on ITS (real) clock while the
    # router runs fake-clocked — zero the failover backoff so deferred
    # resubmissions admit deterministically instead of racing wall time
    router_kw = dict(router_kw or {})
    router_kw.setdefault("failover_backoff_s", 0.0)
    clock = FakeClock()
    hosts, engines = [], []
    for i in range(n):
        eng, params = llama_tiny_host(**fkw)
        engines.append(eng)
        server = HostServer(eng, params, host_id=i,
                            scheduler_config=SchedulerConfig(
                                max_step_retries=1, retry_backoff_s=0.01))
        ep = HostEndpoint(LocalTransport(server), clock=clock,
                          sleep=clock.sleep)
        hosts.append(HostHandle(
            i, ep, health_config=HealthConfig(**(health_kw or {})),
            clock=clock, sleep=clock.sleep))
    router = HostFleetRouter(hosts,
                             config=RouterConfig(**router_kw),
                             clock=clock, sleep=clock.sleep,
                             fault_injector=injector)
    return router, clock, hosts, engines


def _drive(router, clock, dt=0.05, max_steps=500):
    steps = 0
    while router.pending:
        router.step(None)
        clock.advance(dt)
        steps += 1
        assert steps < max_steps, router.statusz()
    return steps


def _prompt(seed=0, n=9):
    rng = np.random.RandomState(seed)
    return rng.randint(1, CFG.vocab_size, (n,)).astype(np.int32)


def _ref_tokens(prompt, max_new, **sub):
    """Fault-free single-host reference stream."""
    router, clock, _, _ = _local_fleet(n=1, max_new=max_new)
    h = router.submit(prompt, max_new_tokens=max_new, **sub)
    _drive(router, clock)
    return list(h.stream.tokens)


def _counter_total(name):
    m = get_registry().get(name)
    return 0.0 if m is None else m.total


# ---------------------------------------------------------------------------
# RPC lifecycle over the wire
# ---------------------------------------------------------------------------

def test_hello_facade_mirrors_engine_geometry():
    router, clock, hosts, engines = _local_fleet(n=1)
    f = hosts[0].engine
    e = engines[0]
    assert f.page_size == e.page_size
    assert f.max_seq_len == e.max_seq_len
    assert f.mgr.usable_pages == e.mgr.usable_pages
    assert f.mgr.pages_for(13) == e.mgr.pages_for(13)
    assert f.config.max_new_tokens == e.config.max_new_tokens
    assert f.has_prefix_cache


def test_submit_step_complete_over_the_wire():
    router, clock, hosts, _ = _local_fleet(n=2, max_new=6)
    h = router.submit(_prompt(), max_new_tokens=6)
    steps = _drive(router, clock)
    assert h.state == "done" and len(h.stream.tokens) == 6
    assert h.stream.tokens == _ref_tokens(_prompt(), 6)
    ep = hosts[h.replica_id].endpoint
    assert ep.calls >= steps          # step heartbeats flowed as frames
    assert ep.bytes_sent > 0 and ep.bytes_received > 0
    st = hosts[h.replica_id].statusz()
    assert st["host"]["host_id"] == h.replica_id
    assert st["transport"]["alive"]


def test_infeasible_request_is_a_caller_error():
    router, clock, hosts, _ = _local_fleet(n=1)
    with pytest.raises(ValueError):
        router.submit(_prompt(n=9), max_new_tokens=10_000)
    for h in hosts:
        assert h.health.state == ReplicaState.HEALTHY


# ---------------------------------------------------------------------------
# heartbeat health: missed beats walk SUSPECT -> EJECTED
# ---------------------------------------------------------------------------

def test_missed_heartbeats_suspect_then_ejected(tmp_path):
    configure_event_log(str(tmp_path / "events.jsonl"))
    try:
        router, clock, hosts, _ = _local_fleet(
            n=2, max_new=8, health_kw={"probe_cooldown_s": 1e9})
        h = router.submit(_prompt(), max_new_tokens=8)
        for _ in range(2):
            router.step(None)
            clock.advance(0.05)
        victim = h.replica_id
        hosts[victim].kill()
        router.step(None)             # first missed beat
        clock.advance(0.05)
        assert hosts[victim].health.state == ReplicaState.SUSPECT
        _drive(router, clock)         # two more misses eject + fail over
        assert hosts[victim].health.state == ReplicaState.EJECTED
        assert h.state == "done" and h.failovers == 1
        # the dead host's gauge reads EJECTED, the survivor HEALTHY
        # (read BEFORE the reference fleet below reuses host id 0)
        g = get_registry().get("paddle_host_state")
        assert g.value(host=str(victim)) == 2.0
        assert g.value(host=str(1 - victim)) == 0.0
        assert h.stream.tokens == _ref_tokens(_prompt(), 8)
        # a dead process's affinity slice is dropped (cold on return)
        assert len(router._index[victim]) == 0
    finally:
        configure_event_log(None)
    events = [json.loads(l) for l in
              (tmp_path / "events.jsonl").read_text().splitlines()]
    lost = [e for e in events if e["kind"] == "host_lost"]
    assert lost and lost[0]["host"] == victim
    assert lost[0]["process_dead"] and lost[0]["inflight"] == 1


# ---------------------------------------------------------------------------
# live migration: pages move, continuation prefills only the tail
# ---------------------------------------------------------------------------

def test_live_migration_byte_identical_with_prefill_skip(tmp_path):
    configure_event_log(str(tmp_path / "events.jsonl"))
    memory_ledger.reset()
    memory_ledger.arm()
    try:
        ref = _ref_tokens(_prompt(), 12)
        router, clock, hosts, engines = _local_fleet(n=2, max_new=12)
        h = router.submit(_prompt(), max_new_tokens=12)
        for _ in range(4):
            router.step(None)
            clock.advance(0.05)
        assert not h.done
        src = h.replica_id
        b0 = _counter_total("paddle_migration_bytes_total")
        p0 = _counter_total("paddle_migration_pages_total")
        c0 = _counter_total("paddle_kvcache_cached_tokens_total")
        summary = router.migrate_host(src)
        assert summary["dst"] != src and summary["requests"] == 1
        assert summary["pages"] >= 1 and summary["failed"] == 0
        assert h.replica_id == summary["dst"]     # continuation landed
        _drive(router, clock)
        assert list(h.stream.tokens) == ref
        # the dst prefill HIT the imported pages instead of recomputing
        served = _counter_total(
            "paddle_kvcache_cached_tokens_total") - c0
        ps = engines[0].page_size
        assert served >= summary["pages"] * ps
        # migration observability: counters, ledger timeline, event
        assert _counter_total(
            "paddle_migration_bytes_total") - b0 == summary["bytes"]
        assert _counter_total(
            "paddle_migration_pages_total") - p0 == summary["pages"]
        mig = memory_ledger.migration_snapshot()
        assert mig["totals"]["pages"] >= summary["pages"]
        assert mig["recent"][-1]["src_host"] == src
        assert mig["recent"][-1]["outcome"] == "ok"
        snap = router.multihost_snapshot()
        assert snap["migrations"][-1]["pages"] == summary["pages"]
        assert router.statusz()["multihost"]["migrated_pages"] == \
            summary["pages"]
        for e in engines:
            e.mgr.check_conservation()
        assert engines[src].mgr.num_live_pages == 0   # src freed
    finally:
        memory_ledger.disarm()
        memory_ledger.reset()
        configure_event_log(None)
    events = [json.loads(l) for l in
              (tmp_path / "events.jsonl").read_text().splitlines()]
    pm = [e for e in events if e["kind"] == "page_migration"]
    assert pm and pm[0]["src"] == src and pm[0]["outcome"] == "ok"
    assert pm[0]["bytes"] > 0 and pm[0]["pages"] == summary["pages"]


def test_migration_affinity_routes_same_prefix_to_dst():
    router, clock, hosts, _ = _local_fleet(
        n=2, max_new=8, router_kw={"load_band": 8})
    h = router.submit(_prompt(), max_new_tokens=8)
    for _ in range(3):
        router.step(None)
        clock.advance(0.05)
    src = h.replica_id
    dst = router.migrate_host(src)["dst"]
    router.undrain(src)
    aff0 = _counter_total("paddle_router_prefix_affinity_hits_total")
    h2 = router.submit(np.concatenate([_prompt(), [3, 4]]).astype(np.int32),
                       max_new_tokens=8)
    assert h2.replica_id == dst       # the pages moved; so does traffic
    assert _counter_total(
        "paddle_router_prefix_affinity_hits_total") - aff0 >= 1
    _drive(router, clock)


def test_migration_failure_falls_back_to_recompute(monkeypatch):
    ref = _ref_tokens(_prompt(), 10)
    router, clock, hosts, engines = _local_fleet(n=2, max_new=10)
    h = router.submit(_prompt(), max_new_tokens=10)
    for _ in range(3):
        router.step(None)
        clock.advance(0.05)
    src = h.replica_id
    dst = 1 - src

    def dying_import(tokens, ks, vs):
        raise HostFault("DCN link dropped mid-transfer")

    monkeypatch.setattr(hosts[dst], "import_prefix", dying_import)
    f0 = _counter_total("paddle_migration_requests_total")
    summary = router.migrate_host(src, dst)
    assert summary["failed"] == 1 and summary["requests"] == 0
    assert _counter_total("paddle_migration_requests_total") - f0 == 1
    _drive(router, clock)
    # recomputed, not lost — and still byte-identical
    assert h.state == "done" and list(h.stream.tokens) == ref
    for e in engines:
        e.mgr.check_conservation()


# ---------------------------------------------------------------------------
# satellite: sampled + grammar-constrained kill replay
# ---------------------------------------------------------------------------

def _abc_grammar():
    from paddle_tpu.inference.constrain import compile_regex
    vocab = ["<eos>"] + list("abcde") + [
        f"tok{i}" for i in range(6, CFG.vocab_size)]
    # three forced pairs before the terminal 'e': every legal stream is
    # 7 tokens + eos, so a mid-stream kill window always exists
    return compile_regex("(ab|cd)(ab|cd)(ab|cd)e", vocab, eos_token_id=0)


def test_sampled_grammar_request_survives_host_kill_byte_identical():
    """Kill a host mid-stream under a SAMPLED, grammar-CONSTRAINED
    request: the continuation must replay the identical stream (seed
    pinned at the router, DFA resumed via grammar_prefix over the
    wire), and a fresh fault-free fleet given the same pinned sampler
    reproduces it byte-for-byte."""
    g = _abc_grammar()

    def fleet():
        return _local_fleet(n=2, max_new=8, eos_token_id=0,
                            grammar_states=g.n_states,
                            health_kw={"probe_cooldown_s": 1e9})

    router, clock, hosts, _ = fleet()
    r1 = router.submit(_prompt(), max_new_tokens=8,
                       sampler=SamplerConfig(temperature=0.8), grammar=g)
    assert r1.sampler.seed is not None        # pinned at the fleet edge
    steps = 0
    while not r1.done:
        router.step(None)
        clock.advance(0.05)
        if len(r1.stream.tokens) >= 2 and r1.failovers == 0 \
                and hosts[r1.replica_id].endpoint.alive():
            hosts[r1.replica_id].kill()       # mid-stream host loss
        steps += 1
        assert steps < 500
    assert r1.failovers >= 1

    router2, clock2, _, _ = fleet()
    r2 = router2.submit(_prompt(), max_new_tokens=8,
                        sampler=r1.sampler, grammar=g)
    _drive(router2, clock2)
    assert r1.stream.tokens == r2.stream.tokens
    # every token grammar-legal end to end
    st = g.start
    for tok in r1.stream.tokens:
        assert g.legal(st, tok)
        st = g.advance(st, tok)


# ---------------------------------------------------------------------------
# host-scoped chaos
# ---------------------------------------------------------------------------

def test_link_slow_injects_latency_then_recovers():
    inj = FaultInjector(schedule=[
        Fault("link_slow", 2, host=0, delay_s=0.2)])
    router, clock, hosts, _ = _local_fleet(n=2, max_new=6, injector=inj)
    h = router.submit(_prompt(), max_new_tokens=6)
    t0 = clock()
    _drive(router, clock)
    assert h.state == "done"
    assert h.stream.tokens == _ref_tokens(_prompt(), 6)
    assert inj.fired == [("link_slow", 2, 0)]
    assert clock() - t0 > 0.2         # the injected latency was paid
    assert hosts[0].health.state == ReplicaState.HEALTHY


def test_host_stall_trips_breaker_then_heals():
    inj = FaultInjector(schedule=[Fault("host_stall", 2, host=0)])
    router, clock, hosts, _ = _local_fleet(
        n=2, max_new=6, injector=inj,
        health_kw={"eject_after": 99},        # stall outlives SUSPECT
        router_kw={"stall_s": 0.2})
    h = router.submit(_prompt(0), max_new_tokens=6)
    h2 = router.submit(_prompt(5), max_new_tokens=6)
    _drive(router, clock)
    assert h.state == "done" and h2.state == "done"
    assert ("host_stall", 2, 0) in inj.fired
    assert hosts[0].health.state == ReplicaState.HEALTHY  # healed


def test_seeded_hosts_schedule_is_deterministic_and_host_unique():
    a = FaultInjector.seeded_hosts(7, num_steps=20, num_hosts=4,
                                   n_faults=3)
    b = FaultInjector.seeded_hosts(7, num_steps=20, num_hosts=4,
                                   n_faults=3)
    sa = [(f.event, f.step, f.host, f.delay_s) for f in a.schedule]
    assert sa == [(f.event, f.step, f.host, f.delay_s)
                  for f in b.schedule]
    hosts_hit = [f.host for f in a.schedule]
    assert len(set(hosts_hit)) == len(hosts_hit)      # <= 1 per host
    for f in a.schedule:
        assert f.event in ("host_die", "host_stall", "link_slow")
        assert (f.delay_s is not None) == (f.event == "link_slow")
        assert 1 <= f.step <= 20


# ---------------------------------------------------------------------------
# chaos acceptance: storm, then prove nothing leaked
# ---------------------------------------------------------------------------

def test_host_die_storm_byte_identical_no_leaks_no_slo_breach(tmp_path):
    """The ISSUE 17 acceptance arc on LocalTransport: a seeded host
    death mid-decode, every request completing byte-identically to the
    fault-free run, the fleet SLO un-breached (failover is remediation),
    zero leaked pages and empty tables on the survivor."""
    prompts = [_prompt(s, n=7 + s % 3) for s in range(3)]
    refs = [_ref_tokens(p, 8) for p in prompts]

    inj = FaultInjector(schedule=[Fault("host_die", 3, host=0)])
    flight_recorder.clear()   # reset the once-per-reason dump latch
    flight_recorder.arm(dump_dir=str(tmp_path))
    br0 = _counter_total("paddle_slo_breaches_total")
    try:
        router, clock, hosts, engines = _local_fleet(
            n=2, max_new=8, injector=inj,
            health_kw={"probe_cooldown_s": 1e9})
        monitor = router.make_slo_monitor(completion_target=0.99)
        handles = [router.submit(p, max_new_tokens=8) for p in prompts]
        _drive(router, clock)
        assert inj.fired == [("host_die", 3, 0)]
        for h, ref in zip(handles, refs):
            assert h.state == "done", h
            assert list(h.stream.tokens) == ref
        # no SLO breach: failovers are remediation, not bad events
        assert router.failed_total == 0 and router.shed_total == 0
        assert _counter_total("paddle_slo_breaches_total") == br0
        assert monitor.health() == "ok"
        # post-storm: no unresolved work, nothing parked, no leaks on
        # the survivor, and its tables are EMPTY
        assert router.pending == 0 and router.parked == 0
        survivor = engines[1]
        survivor.mgr.check_conservation()
        assert survivor.mgr.num_live_pages == 0
        assert survivor.mgr._tables == {}
        # host_lost auto-dump bundle embeds the multihost timeline
        bundles = list(tmp_path.glob("paddle_debug_replica_ejected_0*"))
        assert bundles, list(tmp_path.iterdir())
        with tarfile.open(bundles[0]) as tf:
            mh = json.loads(tf.extractfile("multihost.json").read())
        assert str(0) in mh["hosts"] and "migrations" in mh
        assert mh["hosts"]["0"]["health"]["state"] == "ejected"
    finally:
        flight_recorder.disarm()
        flight_recorder.clear()


# ---------------------------------------------------------------------------
# cross-process liveness: consumers of a dead host terminate
# ---------------------------------------------------------------------------

def test_mirror_stream_closes_producer_dead_without_router():
    """Satellite 6, LocalTransport edition: a consumer holding a dead
    host's stream (no router to fail it over) terminates with a
    structured ``producer_dead`` instead of hanging."""
    eng, params = llama_tiny_host(max_new_tokens=6)
    server = HostServer(eng, params, host_id=0)
    ep = HostEndpoint(LocalTransport(server))
    h = HostHandle(0, ep)
    mirror = h.submit(_prompt(), max_new_tokens=6)
    h.step(None)
    h.kill()
    mirror.stream._poll_s = 0.01
    toks = []
    while True:
        tok = mirror.stream.get(timeout=2.0)
        if tok is None:
            break
        toks.append(tok)
    assert mirror.stream.finished
    assert mirror.stream.error is not None
    assert mirror.stream.error.code == "producer_dead"


# ---------------------------------------------------------------------------
# real processes
# ---------------------------------------------------------------------------

def _spawn_host(i, max_new=10):
    tr = PipeTransport(factory_kwargs={"max_new_tokens": max_new,
                                       "max_seq_len": 48}, host_id=i)
    ep = HostEndpoint(tr, timeout_s=300.0)
    return HostHandle(i, ep,
                      health_config=HealthConfig(probe_cooldown_s=1e9))


def test_two_processes_kill_one_mid_decode_byte_identical():
    """THE acceptance run, for real: two engine processes, a SIGKILL
    mid-decode, and the survivor finishes the stream byte-identically
    to the fault-free run on the same fleet."""
    hosts = [_spawn_host(i) for i in range(2)]
    router = HostFleetRouter(hosts, config=RouterConfig())
    try:
        prompt = _prompt()
        ref = router.submit(prompt, max_new_tokens=10)
        while router.pending:
            router.step(None)
        ref_toks = list(ref.stream.tokens)
        assert len(ref_toks) == 10

        h = router.submit(prompt, max_new_tokens=10)
        steps = 0
        while not h.done:
            router.step(None)
            if len(h.stream.tokens) >= 3 and h.failovers == 0 and \
                    hosts[h.replica_id].endpoint.alive():
                hosts[h.replica_id].kill()    # real SIGKILL
            steps += 1
            assert steps < 1000
        assert h.failovers == 1 and h.state == "done"
        assert list(h.stream.tokens) == ref_toks
        dead = [i for i in range(2)
                if not hosts[i].endpoint.alive()]
        assert len(dead) == 1
    finally:
        router.close()


def test_real_process_death_closes_blocked_consumer():
    """Satellite 6 against a REAL process: a consumer blocked on a
    stream whose producing process got SIGKILLed terminates with
    ``producer_dead`` via the endpoint liveness probe."""
    h = _spawn_host(0, max_new=8)
    try:
        mirror = h.submit(_prompt(), max_new_tokens=8)
        h.step(None)
        h.kill()
        mirror.stream._poll_s = 0.01
        while True:
            tok = mirror.stream.get(timeout=5.0)
            if tok is None:
                break
        assert mirror.stream.finished
        assert mirror.stream.error is not None
        assert mirror.stream.error.code == "producer_dead"
    finally:
        h.close()
