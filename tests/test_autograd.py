"""Autograd tape tests — parity with the reference's eager backward semantics
(check_grad-style numeric oracles, SURVEY.md §4)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.exp(x)
    z = (y * 3).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 3 * np.exp([1.0, 2.0]), rtol=1e-6)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_detach():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    d = y.detach()
    assert d.stop_gradient
    np.testing.assert_allclose(d.numpy(), [6.0])


def test_matmul_grad_numeric():
    rng = np.random.RandomState(0)
    a_np = rng.randn(3, 4).astype(np.float32)
    b_np = rng.randn(4, 5).astype(np.float32)
    a = paddle.to_tensor(a_np, stop_gradient=False)
    b = paddle.to_tensor(b_np, stop_gradient=False)
    out = paddle.matmul(a, b).sum()
    out.backward()
    # analytic: d(sum(AB))/dA = 1 @ B^T
    np.testing.assert_allclose(a.grad.numpy(),
                               np.ones((3, 5)) @ b_np.T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(),
                               a_np.T @ np.ones((3, 5)), rtol=1e-5)


def test_branching_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y1 = x * 3
    y2 = x * 4
    z = (y1 + y2).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_multi_output_op():
    x = paddle.to_tensor(np.array([[3.0, 1.0], [2.0, 4.0]], np.float32),
                         stop_gradient=False)
    vals, idx = paddle.topk(x, 1, axis=1)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0], [0, 1]])


def test_paddle_grad_api():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x ** 3).sum()
    (gx,) = paddle.autograd.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), 3 * np.array([1.0, 4.0]), rtol=1e-6)
    assert x.grad is None  # grad() must not pollute .grad


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(y.numpy(), [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_retain_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])


def test_double_backward_raises():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_grad_does_not_pollute_other_leaves():
    w = paddle.to_tensor([3.0], stop_gradient=False)
    x = paddle.to_tensor([2.0], stop_gradient=False)
    out = (w * x).sum()
    (gx,) = paddle.autograd.grad(out, x, retain_graph=True)
    np.testing.assert_allclose(gx.numpy(), [3.0])
    assert w.grad is None and x.grad is None


def test_grad_of_intermediate():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    h = x * 3
    y = (h * h).sum()
    (gh,) = paddle.autograd.grad(y, h)
    np.testing.assert_allclose(gh.numpy(), [12.0])


def test_backward_through_int_output_op():
    x = paddle.to_tensor(np.array([[3.0, 1.0, 2.0]], np.float32), stop_gradient=False)
    vals, idx = paddle.topk(x, 2)
    # int output idx participates in the node; backward must not crash
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0, 1]])
