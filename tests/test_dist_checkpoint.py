"""Distributed checkpoint tests: save sharded, load under a different
topology (reference: test/auto_parallel semi-auto checkpoint tests)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import distributed as dist
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.checkpoint import (
    flatten_state_dict, load_state_dict, save_state_dict, unflatten_state_dict)
from paddle_tpu.parallel import mesh as pmesh


@pytest.fixture(autouse=True)
def reset_mesh():
    pmesh.set_global_mesh(None)
    yield
    pmesh.set_global_mesh(None)


def _sharded(arr, mesh, spec):
    return Tensor(jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec)))


def test_flatten_roundtrip():
    nested = {"a": 1, "b": {"c": 2, "d": {"e": 3}}}
    flat, mapping = flatten_state_dict(nested)
    assert flat == {"a": 1, "b.c": 2, "b.d.e": 3}
    assert unflatten_state_dict(flat, mapping) == nested


def test_save_load_same_topology(tmp_path):
    mesh = pmesh.build_mesh({"dp": 2, "mp": 4})
    w = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    b = np.random.RandomState(1).randn(8).astype(np.float32)
    sd = {"w": _sharded(w, mesh, P("mp", None)), "b": _sharded(b, mesh, P())}
    save_state_dict(sd, str(tmp_path / "ck"))

    tgt = {"w": _sharded(np.zeros_like(w), mesh, P("mp", None)),
           "b": _sharded(np.zeros_like(b), mesh, P())}
    load_state_dict(tgt, str(tmp_path / "ck"))
    np.testing.assert_allclose(np.asarray(tgt["w"]._value), w)
    np.testing.assert_allclose(np.asarray(tgt["b"]._value), b)


def test_reshard_on_load_different_topology(tmp_path):
    # save under mp=4
    mesh1 = pmesh.build_mesh({"mp": 4})
    w = np.arange(32 * 8, dtype=np.float32).reshape(32, 8)
    sd = {"layer": {"w": _sharded(w, mesh1, P("mp", None))}}
    save_state_dict(sd, str(tmp_path / "ck"))

    # load under dp=2 x sharding=2 x mp=2, sharded on the OTHER dim
    mesh2 = pmesh.build_mesh({"dp": 2, "sharding": 2, "mp": 2})
    tgt = {"layer": {"w": _sharded(np.zeros_like(w), mesh2, P(None, "mp"))}}
    load_state_dict(tgt, str(tmp_path / "ck"))
    got = tgt["layer"]["w"]._value
    np.testing.assert_allclose(np.asarray(got), w)
    # target sharding is preserved
    assert got.sharding.spec == P(None, "mp")


def test_bf16_roundtrip(tmp_path):
    mesh = pmesh.build_mesh({"mp": 8})
    w = (np.random.RandomState(0).randn(8, 4)).astype(jnp.bfloat16)
    sd = {"w": _sharded(w, mesh, P("mp"))}
    save_state_dict(sd, str(tmp_path / "ck"))
    tgt = {"w": _sharded(np.zeros((8, 4), jnp.bfloat16), mesh, P())}
    load_state_dict(tgt, str(tmp_path / "ck"))
    assert tgt["w"]._value.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(tgt["w"]._value, np.float32), np.asarray(w, np.float32))


def test_missing_key_and_shape_mismatch(tmp_path):
    mesh = pmesh.build_mesh({})
    sd = {"w": Tensor(np.zeros((4, 4), np.float32))}
    save_state_dict(sd, str(tmp_path / "ck"))
    with pytest.raises(KeyError):
        load_state_dict({"nope": Tensor(np.zeros((4, 4), np.float32))},
                        str(tmp_path / "ck"))
    with pytest.raises(ValueError):
        load_state_dict({"w": Tensor(np.zeros((2, 4), np.float32))},
                        str(tmp_path / "ck"))


@pytest.mark.slow
def test_model_and_optimizer_state(tmp_path):
    import paddle_tpu.nn as nn
    mesh = pmesh.build_mesh({"sharding": 8})
    pmesh.set_global_mesh(mesh)
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.1,
                                 parameters=model.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16).astype(np.float32))
    loss = model(x).mean()
    loss.backward()
    opt.step()
    sd = {"model": model.state_dict(), "opt": opt.state_dict()}
    save_state_dict(sd, str(tmp_path / "ck"))

    paddle.seed(7)
    model2 = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    # optimizer slot keys embed parameter names, which are generated per
    # process — a real resume recreates them identically in the fresh
    # process; inside this single test process we take the keys from the
    # checkpoint itself. Non-tensor entries ("@step") ride in metadata aux.
    tgt_opt = {k: (Tensor(jnp.zeros_like(v._value))
                   if hasattr(v, "_value") else 0)
               for k, v in sd["opt"].items()}
    tgt = {"model": model2.state_dict(), "opt": tgt_opt}
    load_state_dict(tgt, str(tmp_path / "ck"))
    for k in tgt["model"]:
        np.testing.assert_allclose(np.asarray(tgt["model"][k]._value),
                                   np.asarray(sd["model"][k]._value),
                                   err_msg=k)
    for k in tgt["opt"]:
        if not hasattr(tgt["opt"][k], "_value"):
            continue
        np.testing.assert_allclose(np.asarray(tgt["opt"][k]._value),
                                   np.asarray(sd["opt"][k]._value),
                                   rtol=1e-6, err_msg=k)
    assert tgt["opt"]["@step"] == sd["opt"]["@step"] == 1
