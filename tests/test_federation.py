"""Fleet observability federation (ISSUE 18): per-host telemetry
mirrors, clock-offset estimation, merged cross-host surfaces.

Pure-unit tests exercise :class:`ClockSync` math, exposition merging and
the :class:`FederationHub` mirror lifecycle with fabricated frames
(hermetic registries + collectors, synthetic clock offsets). Fleet tests
run real beats over ``LocalTransport`` — every telemetry frame
round-trips the wire encoder — covering the statusz-staleness satellite,
the heartbeat RTT histogram, and the dead-host ``host_telemetry.json``
bundle round-trip."""

import json
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu.models import llama as L
from paddle_tpu.observability import get_registry
from paddle_tpu.observability.federation import (ClockSync, FederationHub,
                                                 collect_telemetry,
                                                 merge_expositions)
from paddle_tpu.observability.flight import flight_recorder
from paddle_tpu.observability.format import validate_exposition_text
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.observability.signals import SignalBus
from paddle_tpu.observability.timeline import SpanCollector, timeline_armed
from paddle_tpu.serving import (HealthConfig, HostEndpoint, HostFleetRouter,
                                HostHandle, HostServer, LocalTransport,
                                RouterConfig, SchedulerConfig)
from paddle_tpu.serving.multihost import llama_tiny_host

CFG = L.llama_tiny(num_hidden_layers=2)

#: a pid that is never this process (frames from "real" remote hosts)
OTHER_PID = os.getpid() + 1


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def armed_timeline():
    timeline_armed[0] = True
    yield
    timeline_armed[0] = False


def _frame(host_id, seq, spans=(), pid=OTHER_PID, t_ns=None, gauges=None,
           metrics_text="", signals=None):
    return {"host_id": host_id, "pid": pid, "seq": seq,
            "t_ns": 0 if t_ns is None else t_ns,
            "metrics_text": metrics_text, "gauges": dict(gauges or {}),
            "signals": dict(signals or {}), "events": [], "memory": {},
            "spans": list(spans)}


def _span(name, start_ns, end_ns, trace_id="tr", args=None):
    return {"name": name, "event_type": "UserDefined",
            "start_ns": int(start_ns), "end_ns": int(end_ns),
            "trace_id": trace_id, "args": args}


def _hub(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("collector", SpanCollector())
    return FederationHub(**kw)


# ---------------------------------------------------------------------------
# ClockSync: offset from the RPC midpoint, RTT/2 error bound
# ---------------------------------------------------------------------------

def test_clocksync_estimates_offset_from_midpoint():
    cs = ClockSync()
    # remote clock runs 5 ms AHEAD; symmetric 2 ms round-trip
    cs.observe(t_send_ns=1_000_000, t_recv_ns=3_000_000,
               t_remote_ns=2_000_000 + 5_000_000)
    assert cs.offset_ns == pytest.approx(5_000_000)
    assert cs.error_bound_ns == pytest.approx(1_000_000)   # rtt / 2
    # corrected = remote - offset: back in the local domain
    assert cs.correct(10_000_000 + 5_000_000) == pytest.approx(10_000_000)


def test_clocksync_ewma_converges_and_discards_negative_rtt():
    cs = ClockSync(alpha=0.5)
    for i in range(20):
        base = i * 10_000_000
        cs.observe(base, base + 2_000_000, base + 1_000_000 + 7_000_000)
    assert cs.offset_ns == pytest.approx(7_000_000, rel=1e-6)
    n = cs.samples
    cs.observe(5_000_000, 4_000_000, 0)         # clock went backwards
    assert cs.samples == n                      # discarded
    snap = cs.snapshot()
    assert snap["offset_ms"] == pytest.approx(7.0, rel=1e-6)
    assert snap["rtt_p50_ms"] == pytest.approx(2.0, rel=1e-6)


def test_clocksync_rtt_quantiles_over_window():
    cs = ClockSync()
    for ms in (1, 2, 3, 4, 5, 6, 7, 8, 9, 10):
        cs.observe(0, ms * 1_000_000, ms * 500_000)
    assert cs.rtt_quantile(0.5) == pytest.approx(6_000_000)
    assert cs.rtt_quantile(0.9) == pytest.approx(10_000_000)


# ---------------------------------------------------------------------------
# exposition merging: one valid doc, deterministic bytes
# ---------------------------------------------------------------------------

def _exposition(value):
    reg = MetricsRegistry()
    reg.counter("x_total", "x").inc(value)
    reg.gauge("y_gauge", "y", labels=("k",)).set(value, k="a")
    return reg.prometheus_text()


def test_merge_expositions_byte_identical_and_valid():
    docs = {"parent": _exposition(1), "h0": _exposition(2),
            "h1": _exposition(3)}
    merged = merge_expositions(docs)
    validate_exposition_text(merged)
    # deterministic: same docs (any insertion order) -> same bytes
    reordered = {"h1": docs["h1"], "parent": docs["parent"],
                 "h0": docs["h0"]}
    assert merge_expositions(reordered) == merged
    # every sample carries its host, host label FIRST
    assert 'x_total{host="parent"} 1' in merged
    assert 'x_total{host="h0"} 2' in merged
    assert 'y_gauge{host="h1",k="a"} 3' in merged
    # one TYPE line per family across all hosts
    assert merged.count("# TYPE x_total counter") == 1


def test_merge_expositions_preserves_existing_host_label():
    doc = ('# TYPE paddle_host_state gauge\n'
           'paddle_host_state{host="0"} 2\n')
    merged = merge_expositions({"parent": doc})
    validate_exposition_text(merged)
    # the parent's own host-labeled family passes through unchanged
    assert 'paddle_host_state{host="0"} 2' in merged
    assert 'host="parent"' not in merged


def test_merged_histograms_stay_cumulative_per_host():
    def doc(n):
        reg = MetricsRegistry()
        h = reg.histogram("z_seconds", "z", bounds=(1.0, 2.0),
                          quantiles=None)
        for _ in range(n):
            h.observe(1.5)
        return reg.prometheus_text()
    merged = merge_expositions({"h0": doc(1), "h1": doc(3)})
    # host label sits BEFORE le=, so the validator's bucket-monotonicity
    # check runs per host, not across hosts
    validate_exposition_text(merged)
    assert 'z_seconds_bucket{host="h0",le="2"} 1' in merged
    assert 'z_seconds_bucket{host="h1",le="2"} 3' in merged


# ---------------------------------------------------------------------------
# FederationHub: mirrors, skew-corrected span merge, lifecycle
# ---------------------------------------------------------------------------

def test_ingest_merges_remote_spans_skew_corrected(armed_timeline):
    hub = _hub()
    offset = 5_000_000_000          # h7's clock runs 5 s ahead
    hub.observe_rtt(7, 1_000_000, 3_000_000, 2_000_000 + offset)
    spans = [_span("engine.prefill", offset + 100, offset + 200),
             _span("engine.decode_chunk", offset + 300, offset + 400)]
    merged = hub.ingest(7, _frame(7, seq=0, spans=spans))
    assert merged == 2
    got = sorted(hub._collector.spans("tr"), key=lambda s: s.start_ns)
    # timestamps landed back in the LOCAL clock domain
    assert [s.start_ns for s in got] == [100, 300]
    assert [s.end_ns for s in got] == [200, 400]
    # provenance: every merged span is tagged with its host
    assert all(s.args["host"] == 7 for s in got)
    m = hub.mirror(7)
    assert m.frames == 1 and m.spans_merged == 2 and not m.stale


def test_skew_correction_restores_cross_host_ordering(armed_timeline):
    """Property: spans emitted at known TRUE local times, shipped with
    per-host clock offsets, come back correctly ordered after skew
    correction — and each corrected timestamp is inside the estimator's
    error bound."""
    hub = _hub()
    offsets = {0: 3_000_000_000, 1: -2_000_000_000}   # +3 s, -2 s
    rtt = 2_000_000                                   # 2 ms, symmetric
    for hid, off in offsets.items():
        for k in range(8):        # converge the EWMA on exact samples
            base = k * 10_000_000
            hub.observe_rtt(hid, base, base + rtt,
                            base + rtt // 2 + off)
    # interleaved true timeline: (true_start_ns, host)
    truth = [(1_000, 0), (2_000, 1), (3_000, 0), (4_000, 1), (5_000, 0)]
    for seq, (t, hid) in enumerate(truth):
        sp = _span("engine.decode_chunk", offsets[hid] + t,
                   offsets[hid] + t + 500)
        assert hub.ingest(hid, _frame(hid, seq=seq, spans=[sp])) == 1
    got = sorted(hub._collector.spans("tr"), key=lambda s: s.start_ns)
    assert [s.args["host"] for s in got] == [h for _, h in truth]
    bound = max(m.clock.error_bound_ns for m in hub._live_mirrors())
    assert hub.reconcile_error_s() == pytest.approx(bound / 1e9)
    for s, (t, _) in zip(got, truth):
        assert abs(s.start_ns - t) <= bound     # within the stated bound
    assert bound == pytest.approx(rtt / 2)


def test_trace_tree_merge_is_deterministic(armed_timeline):
    """Same frames -> byte-identical merged trace trees."""
    frames = []
    for seq in range(3):
        spans = [_span("paddle_host_h0.request", 1_000, 9_000),
                 _span("engine.prefill", 2_000 + seq, 4_000 + seq)]
        frames.append(_frame(0, seq=seq, spans=spans))
    trees = []
    for _ in range(2):
        hub = _hub()
        for fr in frames:
            hub.ingest(0, dict(fr))
        trees.append(json.dumps(hub._collector.tree("tr"),
                                sort_keys=True))
    assert trees[0] == trees[1]


def test_ingest_dedupes_stale_seq_and_freezes_lost(armed_timeline):
    hub = _hub()
    assert hub.ingest(3, _frame(3, seq=5)) == 0     # no spans, ingested
    assert hub.mirror(3).seq == 5
    sp = [_span("step", 1, 2)]
    assert hub.ingest(3, _frame(3, seq=5, spans=sp)) == 0   # duplicate
    assert hub.mirror(3).frames == 1
    hub.mark_lost(3)
    assert hub.ingest(3, _frame(3, seq=9, spans=sp)) == 0   # frozen
    m = hub.mirror(3)
    assert m.lost and m.stale and m.seq == 5


def test_same_process_frames_skip_span_injection(armed_timeline):
    """LocalTransport mirrors share this process's collector — their
    spans are already there, so re-injection would double-count."""
    hub = _hub()
    fr = _frame(0, seq=0, pid=os.getpid(), spans=[_span("step", 1, 2)])
    assert hub.ingest(0, fr) == 0
    assert hub.mirror(0).frames == 1      # frame still mirrored
    assert hub._collector.spans("tr") == []


def test_mark_stale_keeps_last_frame_and_counts_gauge():
    reg = MetricsRegistry()
    hub = _hub(registry=reg)
    hub.ingest(2, _frame(2, seq=0, gauges={"queue_depth": 4.0}))
    hub.mark_stale(2, "HostFault('no reply')")
    m = hub.mirror(2)
    assert m.stale and m.frame["gauges"]["queue_depth"] == 4.0
    assert m.stale_error == "HostFault('no reply')"
    assert reg.get("paddle_federation_stale_mirrors").value() == 1.0
    hub.ingest(2, _frame(2, seq=1))
    assert not hub.mirror(2).stale
    assert reg.get("paddle_federation_stale_mirrors").value() == 0.0


def test_federated_metrics_text_is_one_valid_doc():
    hub = _hub()
    hub.ingest(0, _frame(0, seq=0, metrics_text=_exposition(2)))
    hub.ingest(1, _frame(1, seq=0, metrics_text=_exposition(5)))
    # a same-process mirror's doc is excluded (families already in the
    # parent text via the shared registry)
    hub.ingest(2, _frame(2, seq=0, pid=os.getpid(),
                         metrics_text=_exposition(9)))
    text = hub.federated_metrics_text()
    validate_exposition_text(text)
    assert 'x_total{host="h0"} 2' in text
    assert 'x_total{host="h1"} 5' in text
    assert 'x_total{host="h2"} 9' not in text
    # the parent's own families are in the same doc: host-labeled series
    # pass through unchanged, unlabeled ones get host="parent"
    assert 'paddle_federation_frames_total{host="h0"} 1' in text
    assert 'paddle_federation_stale_mirrors{host="parent"}' in text


def test_fleet_signals_aggregate_mirrors():
    hub = _hub()
    for hid, (depth, util) in {0: (3.0, 0.5), 1: (5.0, 0.9)}.items():
        hub.observe_rtt(hid, 0, 4_000_000, 2_000_000)    # 4 ms rtt
        hub.ingest(hid, _frame(
            hid, seq=0,
            gauges={"queue_depth": depth, "page_utilization": util},
            signals={"serving.slo_burn": {"value": 0.25 * (hid + 1)}}))
    clock = FakeClock()
    bus = SignalBus(clock=clock, interval_s=0.0)
    hub.attach_fleet_signals(bus)
    bus.arm()
    try:
        clock.advance(1.0)
        bus.tick(clock())
        vals = bus.values()
    finally:
        bus.disarm()
    assert vals["fleet.queue_depth"]["raw"] == pytest.approx(8.0)
    assert vals["fleet.pool_pressure"]["raw"] == pytest.approx(0.9)
    assert vals["fleet.burn_rate"]["raw"] == pytest.approx(0.5)
    assert vals["host_rtt_p90"]["raw"] == pytest.approx(0.004)
    assert vals["h0.queue_depth"]["raw"] == pytest.approx(3.0)
    assert vals["h1.rtt_ms"]["raw"] == pytest.approx(4.0)


def test_snapshot_and_fleet_varz_shapes():
    hub = _hub()
    hub.ingest(0, _frame(0, seq=2))
    hub.mark_lost(1)
    snap = hub.snapshot()
    assert snap["kind"] == "paddle_tpu.host_telemetry"
    assert snap["hosts"]["h0"]["seq"] == 2
    assert snap["hosts"]["h1"]["lost"]
    json.dumps(snap)                    # bundle member must serialize
    fv = hub.fleet_varz()
    assert set(fv) == {"armed", "reconcile_error_ms", "hosts"}
    assert fv["hosts"]["h0"]["frames"] == 1


def test_collect_telemetry_frame_shape():
    reg = MetricsRegistry()
    reg.counter("x_total", "x").inc()
    coll = SpanCollector()
    timeline_armed[0] = True
    try:
        from paddle_tpu.profiler.record import HostSpan
        coll.note_span(HostSpan("engine.prefill", "UserDefined", 1, 2,
                                0, os.getpid(), "tr", None))
        marks = {}
        fr = collect_telemetry(4, marks, seq=0, registry=reg,
                               collector=coll)
        assert fr["host_id"] == 4 and fr["pid"] == os.getpid()
        assert "x_total" in fr["metrics_text"]
        assert [s["name"] for s in fr["spans"]] == ["engine.prefill"]
        # watermarks: a second collection exports nothing new
        fr2 = collect_telemetry(4, marks, seq=1, registry=reg,
                                collector=coll)
        assert fr2["spans"] == []
    finally:
        timeline_armed[0] = False


# ---------------------------------------------------------------------------
# fleet integration over LocalTransport (wire-framed beats, fake clock)
# ---------------------------------------------------------------------------

def _local_fleet(n=2, max_new=8, health_kw=None, **fkw):
    fkw.setdefault("max_new_tokens", max_new)
    fkw.setdefault("max_seq_len", 48)
    clock = FakeClock()
    hosts = []
    for i in range(n):
        eng, params = llama_tiny_host(**fkw)
        server = HostServer(eng, params, host_id=i,
                            scheduler_config=SchedulerConfig(
                                max_step_retries=1, retry_backoff_s=0.01))
        ep = HostEndpoint(LocalTransport(server), clock=clock,
                          sleep=clock.sleep)
        hosts.append(HostHandle(
            i, ep, health_config=HealthConfig(**(health_kw or {})),
            clock=clock, sleep=clock.sleep))
    router = HostFleetRouter(
        hosts, config=RouterConfig(failover_backoff_s=0.0),
        clock=clock, sleep=clock.sleep)
    return router, clock, hosts


def _prompt(seed=0, n=9):
    rng = np.random.RandomState(seed)
    return rng.randint(1, CFG.vocab_size, (n,)).astype(np.int32)


def test_beats_populate_mirrors_and_rtt_histogram():
    router, clock, hosts = _local_fleet(n=2)
    hist = get_registry().get("paddle_host_heartbeat_rtt_seconds")
    c0 = hist.hist(host="h0").count
    router.federation.arm()
    try:
        h = router.submit(_prompt(), max_new_tokens=6)
        for _ in range(4):
            router.step(None)
            clock.advance(0.05)
        for hid in (0, 1):
            m = router.federation.mirror(hid)
            assert m.frames >= 4 and not m.stale
            assert m.frame["gauges"].keys() >= {"queue_depth", "inflight"}
            # the child's namespaced registry families ride along
            assert f"paddle_host_h{hid}" in m.frame["metrics_text"]
        # satellite: the RTT histogram is fed from the same beats
        assert hist.hist(host="h0").count - c0 >= 4
        while router.pending:
            router.step(None)
            clock.advance(0.05)
        assert h.state == "done"
    finally:
        router.federation.disarm()


def test_disarmed_federation_does_no_telemetry_rpcs():
    router, clock, hosts = _local_fleet(n=1)
    router.submit(_prompt(), max_new_tokens=6)
    calls0 = hosts[0].endpoint.calls
    steps = 0
    while router.pending:
        router.step(None)
        clock.advance(0.05)
        steps += 1
    # exactly one RPC per heartbeat: no telemetry traffic while disarmed
    assert hosts[0].endpoint.calls - calls0 == steps


def test_statusz_failure_marks_view_stale_with_counter():
    router, clock, hosts = _local_fleet(n=2)
    c = get_registry().get("paddle_host_statusz_errors_total")
    e0 = c.value(host="h0")
    st = hosts[0].statusz()
    assert st["host"]["host_id"] == 0 and st["host"]["stale"] is False
    t_ok = clock()
    clock.advance(5.0)
    hosts[0].kill()
    st = hosts[0].statusz()
    # unreachable endpoint: cached view, visibly stale, counted
    assert st["host"]["stale"] is True
    assert st["host"]["host_id"] == 0            # last good view kept
    assert "HostFault" in st["host"]["stale_error"]
    assert st["host"]["last_success_t"] == t_ok
    assert c.value(host="h0") - e0 == 1.0


def test_dead_host_bundle_embeds_last_telemetry_mirror(tmp_path):
    """Kill -> eject -> the auto-dumped bundle un-tars with a
    ``host_telemetry.json`` whose dead-host mirror holds the pre-kill
    frame, frozen at mark_lost."""
    flight_recorder.clear()   # reset the once-per-reason dump latch
    flight_recorder.arm(dump_dir=str(tmp_path))
    router, clock, hosts = _local_fleet(
        n=2, health_kw={"probe_cooldown_s": 1e9})
    router.federation.arm()
    try:
        h = router.submit(_prompt(), max_new_tokens=8)
        for _ in range(3):
            router.step(None)
            clock.advance(0.05)
        victim = h.replica_id
        pre_kill_seq = router.federation.mirror(victim).seq
        assert pre_kill_seq >= 0
        hosts[victim].kill()
        steps = 0
        while router.pending:
            router.step(None)
            clock.advance(0.05)
            steps += 1
            assert steps < 500
        assert h.state == "done"
        m = router.federation.mirror(victim)
        assert m.lost and m.seq == pre_kill_seq      # frozen at death
        bundles = list(tmp_path.glob(
            f"paddle_debug_replica_ejected_{victim}*"))
        assert bundles, list(tmp_path.iterdir())
        with tarfile.open(bundles[0]) as tf:
            tel = json.loads(tf.extractfile("host_telemetry.json").read())
        dead = tel["hosts"][f"h{victim}"]
        assert dead["lost"] and dead["seq"] == pre_kill_seq
        assert dead["frame"]["gauges"]["inflight"] >= 1   # pre-kill state
        assert f"paddle_host_h{victim}" in dead["frame"]["metrics_text"]
    finally:
        router.federation.disarm()
        flight_recorder.disarm()
        flight_recorder.clear()


def test_migration_grows_segments_that_tile_the_envelope(tmp_path):
    """LocalTransport edition of the acceptance arc: a mid-stream
    migration under an armed timeline grows ``migration`` +
    ``dcn_transfer`` segments and the exclusive sweep still tiles the
    root envelope exactly."""
    from paddle_tpu.observability.timeline import span_collector
    timeline_armed[0] = True
    router, clock, hosts = _local_fleet(n=2, max_new=12)
    router.federation.arm()
    try:
        h = router.submit(_prompt(), max_new_tokens=12)
        for _ in range(4):
            router.step(None)
            clock.advance(0.05)
        assert not h.done
        summary = router.migrate_host(h.replica_id)
        assert summary["requests"] == 1
        steps = 0
        while router.pending:
            router.step(None)
            clock.advance(0.05)
            steps += 1
            assert steps < 500
        att = span_collector.attribute(h.trace_id)
        segs = att["segments"]
        assert segs.get("migration", 0) > 0
        assert segs.get("dcn_transfer", 0) > 0
        # exclusive segments tile the root envelope exactly
        assert sum(segs.values()) == pytest.approx(att["e2e_ms"],
                                                   rel=1e-6)
    finally:
        router.federation.disarm()
        timeline_armed[0] = False
