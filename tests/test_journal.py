"""Black-box journal + postmortem replay (ISSUE 20).

Three layers of coverage:

1. pure frame-codec units — ring bounds/rotation accounting, every
   structured ``decode_journal`` rejection (truncation, version skew,
   per-line corruption, schema, seq gap — mirroring test_wire.py's
   torn-frame matrix), ``first_divergence`` semantics (extension-OK);
2. the FaultInjector record surface — legacy ``fired`` tuples stay
   byte-for-byte what chaos tests assert on, ``fired_records`` carry
   stable ids, seeded schedules JSON-round-trip with version skew
   rejected;
3. the chaos-arc acceptance: the 4-replica ejection incident from
   tests/test_router.py runs once with the journal armed (module
   fixture); its bundles validate, the final bundle replays
   byte-identically with zero leaked pages, the mid-incident ejection
   bundle replays as a clean prefix, and planted divergences (flipped
   token, dropped chaos frame) are localized to the exact (step,
   replica, component).
"""

import io
import json
import os
import tarfile
import zlib

import numpy as np
import pytest

from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                           GenerationConfig)
from paddle_tpu.models import llama as L
from paddle_tpu.observability.events import configure_event_log
from paddle_tpu.observability.flight import (BUNDLE_SCHEMAS, BundleError,
                                             flight_recorder,
                                             validate_bundle)
from paddle_tpu.observability.journal import (JOURNAL_VERSION,
                                              JournalError,
                                              JournalRecorder,
                                              canonical_frame,
                                              decode_journal,
                                              encode_frames,
                                              first_divergence, journal,
                                              model_spec, token_checksum)
from paddle_tpu.observability.replay import (replay_bundle,
                                             replay_journal)
from paddle_tpu.resilience import Fault, FaultInjector
from paddle_tpu.resilience.faults import FAULTS_SCHEMA_VERSION
from paddle_tpu.serving import (FleetRouter, HealthConfig, ReplicaHandle,
                                RouterConfig, SchedulerConfig)

MAX_NEW = 8
SEED = 3
CFG = L.llama_tiny(num_hidden_layers=2)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _step_frame(seq, step, clock=1000.0):
    return {"t": "step", "seq": seq, "step": step, "clock": clock}


def _journal_bytes(frames, head=None):
    return encode_frames(head or {"model": None, "fleet": None}, frames)


def _rewrite_member(src_path, dst_path, name, data):
    """Copy a bundle tarball with one member's bytes replaced."""
    with tarfile.open(src_path, "r:gz") as src, \
            tarfile.open(dst_path, "w:gz") as dst:
        for m in src.getmembers():
            buf = src.extractfile(m).read()
            if os.path.basename(m.name) == name:
                buf = data
                m.size = len(buf)
            dst.addfile(m, io.BytesIO(buf))
    return dst_path


# ---------------------------------------------------------------------------
# token_checksum + frame signing
# ---------------------------------------------------------------------------

def test_token_checksum_is_stable_across_input_types():
    toks = [5, 17, 9000, 3]
    crc = token_checksum(toks)
    assert crc == token_checksum(np.asarray(toks, np.int32))
    assert crc == token_checksum(tuple(toks))
    assert crc != token_checksum(list(reversed(toks)))
    assert 0 <= crc <= 0xFFFFFFFF


def test_encode_decode_round_trip_preserves_frames_and_head():
    frames = [_step_frame(1, 1), _step_frame(2, 2, 1000.1),
              {"t": "outcome", "seq": 3, "step": 2, "rid": 0,
               "tokens": [1, 2, 3], "stream_crc": token_checksum([1, 2, 3])}]
    head = {"model": {"arch": "X"}, "fleet": {"router_kind": "FleetRouter"}}
    dec = decode_journal(encode_frames(head, frames))
    assert dec.head == head
    assert dec.dropped == 0
    assert [canonical_frame(f) for f in dec.frames] \
        == [canonical_frame(f) for f in frames]
    # every line carries its own crc
    assert all("crc" in f for f in dec.frames)


# ---------------------------------------------------------------------------
# ring bounds + rotation
# ---------------------------------------------------------------------------

def test_ring_bounds_drop_oldest_and_decode_reports_rotation():
    rec = JournalRecorder(capacity=8)
    rec.record_head(model=None, fleet=None)
    for s in range(1, 21):
        rec.note_step(s, 1000.0 + s)
    assert len(rec.frames()) == 8            # bounded: oldest evicted
    assert rec.dropped == 12
    dec = decode_journal(rec.encode())
    assert dec.dropped == 12                 # first surviving seq is 13
    assert int(dec.frames[0]["seq"]) == 13
    # a rotated window is incomplete — replay must refuse, not guess
    rep = replay_journal(dec)
    assert rep.refused is not None and rep.refused["code"] == "rotated"


def test_record_head_resets_ring_to_one_incident_window():
    rec = JournalRecorder(capacity=16)
    rec.record_head(model="a", fleet=None)
    rec.note_step(1, 1.0)
    rec.record_head(model="b", fleet=None)
    assert rec.frames() == []
    assert rec.dropped == 0
    assert decode_journal(rec.encode()).head["model"] == "b"


def test_snapshot_status_reports_ring_occupancy():
    rec = JournalRecorder(capacity=4)
    rec.record_head(model=None, fleet=None)
    rec.note_step(1, 1.0)
    st = rec.snapshot_status()
    assert st["capacity"] == 4 and st["frames"] == 1
    assert st["journal_version"] == JOURNAL_VERSION
    assert st["dropped"] == 0 and st["head"] is True


# ---------------------------------------------------------------------------
# versioned decode: the rejection matrix (mirrors test_wire.py)
# ---------------------------------------------------------------------------

def test_decode_rejects_empty_and_torn_journals(tmp_path):
    with pytest.raises(JournalError) as ei:
        decode_journal(b"")
    assert ei.value.code == "truncated"

    good = _journal_bytes([_step_frame(1, 1)])
    with pytest.raises(JournalError) as ei:
        decode_journal(good[:-1])            # no trailing newline
    assert ei.value.code == "truncated"

    # a torn final write (power-loss analogue) emits journal_truncated
    log = tmp_path / "events.jsonl"
    configure_event_log(str(log))
    try:
        with pytest.raises(JournalError) as ei:
            decode_journal(good[:-7])        # cut mid-last-line
        assert ei.value.code == "truncated"
    finally:
        configure_event_log(None)
    kinds = [json.loads(x)["kind"] for x in log.read_text().splitlines()]
    assert "journal_truncated" in kinds


def test_decode_rejects_version_skew():
    body = {"t": "head", "seq": 0, "journal_version": 99}
    canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(canon.encode()) & 0xFFFFFFFF
    line = json.dumps({**body, "crc": crc}, sort_keys=True,
                      separators=(",", ":"))
    with pytest.raises(JournalError) as ei:
        decode_journal((line + "\n").encode())
    assert ei.value.code == "version_skew"


def test_decode_rejects_per_line_corruption_without_resign():
    data = _journal_bytes([_step_frame(1, 1, clock=1.0),
                           _step_frame(2, 2, clock=2.0)])
    assert b'"clock":1.0' in data
    with pytest.raises(JournalError) as ei:
        decode_journal(data.replace(b'"clock":1.0', b'"clock":9.0'))
    assert ei.value.code == "checksum_mismatch"


def test_decode_rejects_interior_garbage_as_schema_not_truncation():
    lines = _journal_bytes([_step_frame(1, 1)]).splitlines()
    doctored = b"\n".join([lines[0], b"!! not json !!", lines[1]]) + b"\n"
    with pytest.raises(JournalError) as ei:
        decode_journal(doctored)
    assert ei.value.code == "schema"

    # a journal whose first frame is not a head frame is malformed
    no_head = ("\n".join(
        l.decode() for l in _journal_bytes(
            [_step_frame(1, 1)]).splitlines()[1:]) + "\n").encode()
    with pytest.raises(JournalError) as ei:
        decode_journal(no_head)
    assert ei.value.code == "schema"


def test_decode_rejects_mid_journal_seq_gap():
    data = _journal_bytes([_step_frame(1, 1), _step_frame(2, 2),
                           _step_frame(4, 4)])
    with pytest.raises(JournalError) as ei:
        decode_journal(data)
    assert ei.value.code == "gap"


# ---------------------------------------------------------------------------
# first_divergence semantics
# ---------------------------------------------------------------------------

def test_first_divergence_extension_is_not_a_divergence():
    j = [_step_frame(1, 1)]
    o = [_step_frame(1, 1), _step_frame(2, 2)]
    assert first_divergence(j, o) is None     # mid-incident prefix rule
    # but the journal claiming MORE than observed is a divergence
    d = first_divergence(o, j)
    assert d is not None and d.index == 1 and d.component == "step"
    assert d.observed is None


def test_first_divergence_ignores_transport_fields_and_localizes():
    out = {"t": "outcome", "seq": 5, "step": 7, "replica": 2, "rid": 0,
           "tokens": [1, 2], "stream_crc": token_checksum([1, 2])}
    twin = dict(out, seq=9, crc=123)          # same payload, new transport
    assert first_divergence([out], [twin]) is None
    flipped = dict(out, tokens=[1, 3])
    d = first_divergence([out], [flipped])
    assert (d.step, d.replica, d.component) == (7, 2, "outcome")
    assert d.journaled["tokens"] == [1, 2]
    assert d.observed["tokens"] == [1, 3]


# ---------------------------------------------------------------------------
# replay refusals for structurally un-replayable windows
# ---------------------------------------------------------------------------

def test_replay_refuses_scale_and_handoff_windows():
    scale = {"t": "scale", "seq": 1, "step": 2, "scale_seq": 1,
             "action": "scale_up", "reason": "queue", "replica": None,
             "role": None}
    rep = replay_journal(decode_journal(_journal_bytes([scale])))
    assert rep.refused["code"] == "topology_changed"

    handoff = {"t": "handoff", "seq": 1, "step": 2, "rid": 0, "src": 0,
               "dst": 1, "pages": 3, "outcome": "ok"}
    rep = replay_journal(decode_journal(_journal_bytes([handoff])))
    assert rep.refused["code"] == "disagg"


def test_replay_refuses_bundle_without_journal(tmp_path):
    assert not journal.armed
    flight_recorder.arm(dump_dir=str(tmp_path))
    try:
        path = flight_recorder.dump_debug_bundle(reason="no_journal")
    finally:
        flight_recorder.disarm()
    rep = replay_bundle(path)
    assert rep.refused["code"] == "no_journal"


# ---------------------------------------------------------------------------
# FaultInjector: legacy tuples, stable ids, JSON round-trip
# ---------------------------------------------------------------------------

def test_fired_tuples_keep_legacy_shape_and_records_get_stable_ids():
    inj = FaultInjector(schedule=[Fault("replica_die", 3, replica=1),
                                  Fault("preempt", 2)])
    assert inj.fire("preempt", 2)             # unscoped trainer fault
    assert inj.fire("replica_die", 3, replica=1)
    assert not inj.fire("replica_die", 3, replica=1)   # one-shot
    # the tuples chaos tests assert on — shape is frozen
    assert inj.fired == [("preempt", 2), ("replica_die", 3, 1)]
    assert [r["id"] for r in inj.fired_records] \
        == ["preempt@s2:r-:c-:h-", "replica_die@s3:r1:c-:h-"]
    assert inj.fired_records[1]["replica"] == 1
    assert inj.fired_records[1]["chip"] is None


def test_seeded_schedule_json_round_trip():
    inj = FaultInjector.seeded_replicas(seed=7, num_steps=12,
                                        num_replicas=4, n_faults=2)
    assert inj.fire(inj.schedule[0].event, inj.schedule[0].step,
                    replica=inj.schedule[0].replica)
    doc = json.loads(json.dumps(inj.to_json()))
    assert doc["schema_version"] == FAULTS_SCHEMA_VERSION
    inj2 = FaultInjector.from_json(doc)
    # the REMAINING schedule survives (consumed faults are gone) ...
    assert inj2.schedule == inj.schedule
    assert len(inj2.schedule) == 1
    # ... and the resolved fired records ride along
    assert inj2.fired_records == inj.fired_records


def test_from_json_rejects_schema_version_skew():
    doc = FaultInjector(schedule=[Fault("preempt", 1)]).to_json()
    doc["schema_version"] = FAULTS_SCHEMA_VERSION + 1
    with pytest.raises(ValueError, match="schema_version"):
        FaultInjector.from_json(doc)


# ---------------------------------------------------------------------------
# the chaos-arc acceptance: run the ejection incident ONCE, replay it
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    advance = sleep


def _chaos_fleet(injector):
    params = L.init_stacked_params(CFG, seed=SEED)
    clock = _Clock()
    replicas = [
        ReplicaHandle(
            i,
            ContinuousBatchingEngine(
                CFG, GenerationConfig(max_new_tokens=MAX_NEW, seed=SEED),
                num_slots=2, page_size=4, max_seq_len=32, chunk=2),
            config=SchedulerConfig(max_step_retries=1,
                                   retry_backoff_s=0.01),
            health_config=HealthConfig(suspect_after=1, eject_after=2,
                                       probe_cooldown_s=0.4),
            clock=clock, sleep=clock.sleep)
        for i in range(4)]
    router = FleetRouter(
        replicas, config=RouterConfig(failover_backoff_s=0.05, stall_s=0.5),
        clock=clock, sleep=clock.sleep, fault_injector=injector)
    return params, router, clock


@pytest.fixture(scope="module")
def incident(tmp_path_factory):
    """The journaled 4-replica chaos run (replica 1 dies mid-decode at
    step 3, replica 2 stalls at step 5): ejection auto-dump bundle +
    final manual bundle, run once per module."""
    dump_dir = str(tmp_path_factory.mktemp("incident"))
    injector = FaultInjector(schedule=[Fault("replica_die", 3, replica=1),
                                       Fault("replica_stall", 5, replica=2)])
    params, router, clock = _chaos_fleet(injector)
    rng = np.random.RandomState(31)
    prompts = [rng.randint(1, CFG.vocab_size,
                           (int(rng.randint(4, 9)),)).astype(np.int32)
               for _ in range(12)]
    submissions = {0: prompts[:8], 6: prompts[8:10], 16: prompts[10:]}

    flight_recorder.arm(dump_dir=dump_dir)
    journal.arm(capacity=8192)
    journal.record_head(model=model_spec(CFG, SEED),
                        fleet=router.journal_topology())
    try:
        handles, step = [], 0
        while step < 300:
            for p in submissions.pop(step, []):
                handles.append(router.submit(p))
            if not submissions and not router.pending:
                break
            router.step(params)
            clock.advance(0.05)
            step += 1
        assert step < 300, router.statusz()
        final = flight_recorder.dump_debug_bundle(reason="test_final")
    finally:
        journal.disarm()
        flight_recorder.disarm()
    streams = [list(h.stream.result()) for h in handles]
    assert all(len(s) == MAX_NEW for s in streams)
    ejection = os.path.join(
        dump_dir,
        [f for f in os.listdir(dump_dir) if "replica_ejected" in f][0])
    return {"streams": streams, "ejection": ejection, "final": final,
            "fired": [dict(r) for r in injector.fired_records],
            "dir": dump_dir}


def test_incident_bundles_validate_and_stamp_every_member(incident):
    for path in (incident["ejection"], incident["final"]):
        doc = validate_bundle(path)
        svs = doc["manifest"]["schema_versions"]
        # EVERY member is accounted for at a version this tree speaks
        assert set(svs) == set(doc["members"])
        for name, ver in svs.items():
            assert ver == BUNDLE_SCHEMAS.get(name, ver)
        assert doc["journal"] is not None


def test_incident_journal_frames_carry_the_nondeterminism_frontier(incident):
    dec = validate_bundle(incident["final"])["journal"]
    by_type = {}
    for f in dec.frames:
        by_type.setdefault(f["t"], []).append(f)
    arrivals = by_type["arrival"]
    assert len(arrivals) == 12
    for a in arrivals:
        assert a["prompt_crc"] == token_checksum(a["prompt"])
    # the consumed chaos faults, nested with their resolved stable ids
    ids = [f["fault"]["id"] for f in by_type["fault"]]
    assert ids == [r["id"] for r in incident["fired"]]
    assert "replica_die@s3:r1:c-:h-" in ids
    # replica 1's breaker walked healthy -> suspect -> ejected
    walk = [(h["prev"], h["state"]) for h in by_type["health"]
            if h["replica"] == 1]
    assert ("suspect", "ejected") in walk
    # terminal outcomes: stream crc matches tokens, engine crc agrees
    outcomes = by_type["outcome"]
    assert len(outcomes) == 12
    for o in outcomes:
        assert o["stream_crc"] == token_checksum(o["tokens"])
        if o["engine_crc"] is not None and o["failovers"] == 0:
            assert o["engine_crc"] == o["stream_crc"]


def test_final_bundle_replays_byte_identical_with_zero_leaks(incident):
    rep = replay_bundle(incident["final"])
    assert rep.refused is None, rep.refused
    assert rep.divergence is None, rep.divergence
    assert rep.ok
    assert rep.replicas == 4 and rep.arrivals == 12 and rep.outcomes == 12
    assert rep.pending == 0
    assert rep.leaked_pages == 0 and rep.conservation == "ok"


def test_ejection_bundle_replays_as_clean_prefix(incident):
    rep = replay_bundle(incident["ejection"])
    assert rep.refused is None, rep.refused
    # observed frames extend past the mid-incident journal: NOT a
    # divergence (the dump happened with requests still in flight)
    assert rep.divergence is None, rep.divergence
    assert rep.conservation == "ok"
    assert rep.pending > 0          # the incident was still running


def test_planted_flipped_token_localizes_to_exact_frame(incident, tmp_path):
    decoded = validate_bundle(incident["final"])["journal"]
    frames = [dict(f) for f in decoded.frames]
    target = next(f for f in frames if f["t"] == "outcome")
    target["tokens"] = list(target["tokens"])
    target["tokens"][0] ^= 1
    doctored = _rewrite_member(
        incident["final"], str(tmp_path / "flipped.tar.gz"),
        "journal.jsonl", encode_frames(decoded.head, frames))
    rep = replay_bundle(doctored)
    d = rep.divergence
    assert d is not None and not rep.ok
    assert (d.step, d.replica, d.component) \
        == (target["step"], target["replica"], "outcome")
    assert d.journaled["tokens"] != d.observed["tokens"]


def test_dropped_chaos_frame_localizes_to_health_divergence(incident,
                                                           tmp_path):
    """Remove the replica_die fault frame from the journal: replay
    rebuilds an injector without the death, replica 1 stays healthy,
    and the first divergence is the journaled breaker transition that
    never happens."""
    decoded = validate_bundle(incident["final"])["journal"]
    frames = [dict(f) for f in decoded.frames
              if not (f["t"] == "fault"
                      and f["fault"]["event"] == "replica_die")]
    for seq, f in enumerate(frames, start=1):
        f["seq"] = seq              # canonical compare ignores seq
    doctored = _rewrite_member(
        incident["final"], str(tmp_path / "dropped.tar.gz"),
        "journal.jsonl", encode_frames(decoded.head, frames))
    rep = replay_bundle(doctored)
    d = rep.divergence
    assert d is not None and not rep.ok
    assert d.component == "health" and d.replica == 1
    assert d.journaled["state"] == "suspect"


def test_replay_cli_reports_ok_and_divergence(incident, tmp_path, capsys):
    from paddle_tpu.observability.replay import main
    assert main([incident["final"], "--json"]) == 0
    body = json.loads(capsys.readouterr().out)
    assert body["ok"] and body["divergence"] is None

    decoded = validate_bundle(incident["final"])["journal"]
    frames = [dict(f) for f in decoded.frames]
    target = next(f for f in frames if f["t"] == "outcome")
    target["stream_crc"] ^= 1
    doctored = _rewrite_member(
        incident["final"], str(tmp_path / "crc.tar.gz"),
        "journal.jsonl", encode_frames(decoded.head, frames))
    assert main([doctored]) == 1
    out = capsys.readouterr().out
    assert "divergence" in out.lower()


# ---------------------------------------------------------------------------
# doctored bundles: the shared validator rejects skew + missing manifest
# ---------------------------------------------------------------------------

def test_validate_bundle_rejects_member_version_skew(incident, tmp_path):
    doc = validate_bundle(incident["final"])
    manifest = json.loads(doc["members"]["manifest.json"])
    manifest["schema_versions"]["metrics.json"] = 99
    doctored = _rewrite_member(
        incident["final"], str(tmp_path / "skew.tar.gz"),
        "manifest.json", json.dumps(manifest, indent=1).encode())
    with pytest.raises(BundleError) as ei:
        validate_bundle(doctored)
    assert ei.value.code == "version_skew"
    # replay_bundle surfaces it as a structured refusal, not a crash
    rep = replay_bundle(doctored)
    assert rep.refused["code"] == "bundle:version_skew"


def test_validate_bundle_rejects_manifest_without_schema_map(incident,
                                                            tmp_path):
    doc = validate_bundle(incident["final"])
    manifest = json.loads(doc["members"]["manifest.json"])
    del manifest["schema_versions"]
    doctored = _rewrite_member(
        incident["final"], str(tmp_path / "nomap.tar.gz"),
        "manifest.json", json.dumps(manifest, indent=1).encode())
    with pytest.raises(BundleError) as ei:
        validate_bundle(doctored)
    assert ei.value.code == "schema"


def test_validate_bundle_rejects_torn_journal_member(incident, tmp_path):
    doc = validate_bundle(incident["final"])
    torn = doc["members"]["journal.jsonl"][:-9]
    doctored = _rewrite_member(
        incident["final"], str(tmp_path / "torn.tar.gz"),
        "journal.jsonl", torn)
    with pytest.raises(JournalError) as ei:
        validate_bundle(doctored)
    assert ei.value.code == "truncated"
    rep = replay_bundle(doctored)
    assert rep.refused["code"] == "journal:truncated"
