"""Varlen/ragged flash attention (VERDICT round-2 item 4).

Reference surface: python/paddle/nn/functional/flash_attention.py
(flash_attn_unpadded + ragged shapes). On CPU these exercise the padding /
segment-mask reference path; the Pallas kernel parity runs on the chip
(benchmarks/bench_kernels.py varlen section).
"""

import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.ops import flash_attention as fa


def _ref_one(q, k, v, causal):
    """Single-sequence oracle, (S, H, D) layout."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = np.einsum("thd,shd->hts", q.astype(np.float64),
                  k.astype(np.float64)) * scale
    if causal:
        tq, tk = q.shape[0], k.shape[0]
        mask = np.tril(np.ones((tq, tk), bool), k=tk - tq)
        s = np.where(mask[None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("hts,shd->thd", p, v.astype(np.float64))


class TestRaggedPadding:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("s", [96, 200])
    def test_bhsd_ragged_matches_reference(self, causal, s):
        """S % 128 != 0 must run via pad+mask+slice, exactly."""
        rs = np.random.RandomState(0)
        q = rs.randn(2, s, 64).astype(np.float32)
        k = rs.randn(2, s, 64).astype(np.float32)
        v = rs.randn(2, s, 64).astype(np.float32)
        out = fa.flash_attention_bhsd(jnp.asarray(q), jnp.asarray(k),
                                      jnp.asarray(v), 0.125, causal)
        ref = fa._attn_ref(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           0.125, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("sq,sk", [(1, 200), (64, 256), (100, 160)])
    def test_decode_style_causal_end_aligned(self, sq, sk):
        """sq != sk causal (KV-cache decode) keeps _attn_ref's END-aligned
        convention: row i attends cols <= i + (sk - sq) — the round-3
        pad+mask path must not regress it to top-left alignment."""
        rs = np.random.RandomState(7)
        q = jnp.asarray(rs.randn(2, sq, 64).astype(np.float32))
        k = jnp.asarray(rs.randn(2, sk, 64).astype(np.float32))
        v = jnp.asarray(rs.randn(2, sk, 64).astype(np.float32))
        out = fa.flash_attention_bhsd(q, k, v, 0.125, True)
        ref = fa._attn_ref(q, k, v, 0.125, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_bhsd_ragged_grads_exact(self):
        rs = np.random.RandomState(1)
        q = jnp.asarray(rs.randn(2, 100, 64).astype(np.float32))
        k = jnp.asarray(rs.randn(2, 100, 64).astype(np.float32))
        v = jnp.asarray(rs.randn(2, 100, 64).astype(np.float32))

        def f_new(q, k, v):
            return (fa.flash_attention_bhsd(q, k, v, 0.125, True)
                    .astype(jnp.float32) ** 2).sum()

        def f_ref(q, k, v):
            return (fa._attn_ref(q, k, v, 0.125, True)
                    .astype(jnp.float32) ** 2).sum()

        g_new = jax.grad(f_new, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_new, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


class TestVarlen:
    @pytest.mark.parametrize("causal", [True, False])
    def test_varlen_matches_per_sequence_oracle(self, causal):
        rs = np.random.RandomState(2)
        lens = [5, 9, 3]
        H, D = 4, 32
        total = sum(lens)
        q = rs.randn(total, H, D).astype(np.float32)
        k = rs.randn(total, H, D).astype(np.float32)
        v = rs.randn(total, H, D).astype(np.float32)
        cu = np.cumsum([0] + lens).astype(np.int32)
        out = fa.flash_attention_varlen(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(cu), jnp.asarray(cu), causal=causal)
        out = np.asarray(out)
        for i in range(len(lens)):
            a, b = cu[i], cu[i + 1]
            ref = _ref_one(q[a:b], k[a:b], v[a:b], causal)
            np.testing.assert_allclose(out[a:b], ref, rtol=2e-5, atol=2e-5,
                                       err_msg=f"sequence {i}")

    def test_varlen_blocks_cross_sequence_attention(self):
        """Moving tokens of sequence 2 must not change sequence 1's out."""
        rs = np.random.RandomState(3)
        H, D = 2, 32
        q = rs.randn(12, H, D).astype(np.float32)
        k = rs.randn(12, H, D).astype(np.float32)
        v = rs.randn(12, H, D).astype(np.float32)
        cu = np.asarray([0, 7, 12], np.int32)
        out1 = np.asarray(fa.flash_attention_varlen(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(cu), jnp.asarray(cu), causal=True))
        k2, v2 = k.copy(), v.copy()
        k2[7:] = rs.randn(5, H, D)
        v2[7:] = rs.randn(5, H, D)
        out2 = np.asarray(fa.flash_attention_varlen(
            jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2),
            jnp.asarray(cu), jnp.asarray(cu), causal=True))
        np.testing.assert_allclose(out1[:7], out2[:7], rtol=1e-6)
        assert not np.allclose(out1[7:], out2[7:])

    @pytest.mark.slow
    def test_varlen_grads_match_oracle(self):
        rs = np.random.RandomState(4)
        lens = [6, 10]
        H, D = 2, 32
        total = sum(lens)
        cu = jnp.asarray(np.cumsum([0] + lens).astype(np.int32))
        q = jnp.asarray(rs.randn(total, H, D).astype(np.float32))
        k = jnp.asarray(rs.randn(total, H, D).astype(np.float32))
        v = jnp.asarray(rs.randn(total, H, D).astype(np.float32))

        def f(q, k, v):
            out = fa.flash_attention_varlen(q, k, v, cu, cu, causal=True)
            return (out.astype(jnp.float32) ** 2).sum()

        grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        def f_oracle(q, k, v):
            tot = 0.0
            for i in range(len(lens)):
                a, b = int(cu[i]), int(cu[i + 1])
                scale = 1.0 / math.sqrt(D)
                s = jnp.einsum("thd,shd->hts",
                               q[a:b].astype(jnp.float32),
                               k[a:b].astype(jnp.float32)) * scale
                m = jnp.tril(jnp.ones((b - a, b - a), bool))
                s = jnp.where(m[None], s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("hts,shd->thd", p,
                               v[a:b].astype(jnp.float32))
                tot = tot + (o ** 2).sum()
            return tot

        ref = jax.grad(f_oracle, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(grads, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_causal_requires_matching_packings(self):
        """cu_seqlens_q != cu_seqlens_k with causal=True is ill-defined in
        packed coordinates — must raise, not silently zero-mask."""
        rs = np.random.RandomState(6)
        q = jnp.asarray(rs.randn(7, 2, 32).astype(np.float32))
        cu_q = jnp.asarray(np.asarray([0, 2, 7], np.int32))
        cu_k = jnp.asarray(np.asarray([0, 5, 7], np.int32))
        with pytest.raises(ValueError, match="self-attention packing"):
            fa.flash_attention_varlen(q, q, q, cu_q, cu_k, causal=True)

    def test_public_unpadded_api(self):
        rs = np.random.RandomState(5)
        lens = [4, 8]
        cu = paddle.to_tensor(np.cumsum([0] + lens).astype(np.int32))
        q = paddle.to_tensor(rs.randn(12, 2, 32).astype(np.float32))
        out, _ = F.flash_attn_unpadded(q, q, q, cu, cu, causal=True)
        assert tuple(out.shape) == (12, 2, 32)
        with pytest.raises(NotImplementedError, match="dropout"):
            F.flash_attn_unpadded(q, q, q, cu, cu, dropout=0.5)
