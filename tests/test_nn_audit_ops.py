"""Round-2 nn.functional audit batch: N-D pooling, conv transposes,
activations, loss zoo, CTC (vs brute-force path enumeration)."""

import itertools

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

R = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_pool_1d_3d():
    x1 = _t(R.randn(2, 3, 8).astype(np.float32))
    assert tuple(F.max_pool1d(x1, 2).shape) == (2, 3, 4)
    assert tuple(F.avg_pool1d(x1, 2).shape) == (2, 3, 4)
    assert tuple(F.adaptive_avg_pool1d(x1, 2).shape) == (2, 3, 2)
    assert tuple(F.adaptive_max_pool1d(x1, 4).shape) == (2, 3, 4)
    x3 = _t(R.randn(1, 2, 4, 4, 4).astype(np.float32))
    assert tuple(F.max_pool3d(x3, 2).shape) == (1, 2, 2, 2, 2)
    assert tuple(F.avg_pool3d(x3, 2).shape) == (1, 2, 2, 2, 2)
    assert tuple(F.adaptive_avg_pool3d(x3, 2).shape) == (1, 2, 2, 2, 2)
    # avg matches numpy on a simple case
    got = np.asarray(F.avg_pool1d(x1, 2)._value)
    ref = np.asarray(x1._value).reshape(2, 3, 4, 2).mean(-1)
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_conv_transposes_roundtrip_shapes():
    x = _t(R.randn(1, 4, 5).astype(np.float32))
    w = _t((R.randn(4, 3, 2) * 0.1).astype(np.float32))  # [in, out, k]
    y = F.conv1d_transpose(x, w, stride=2)
    assert tuple(y.shape) == (1, 3, 10)
    x3 = _t(R.randn(1, 2, 3, 3, 3).astype(np.float32))
    w3 = _t((R.randn(2, 2, 2, 2, 2) * 0.1).astype(np.float32))
    y3 = F.conv3d_transpose(x3, w3, stride=2)
    assert tuple(y3.shape) == (1, 2, 6, 6, 6)


def test_activations():
    x = _t(R.randn(4, 6).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(F.log_sigmoid(x)._value),
        np.asarray(jax.nn.log_sigmoid(np.asarray(x._value))), rtol=1e-5)
    g = F.glu(x, axis=-1)
    assert tuple(g.shape) == (4, 3)
    mo = F.maxout(_t(R.randn(2, 6, 3).astype(np.float32)), groups=3, axis=1)
    assert tuple(mo.shape) == (2, 2, 3)
    tr = np.asarray(F.thresholded_relu(_t(np.asarray([0.5, 2.0],
                                                     np.float32)))._value)
    np.testing.assert_allclose(tr, [0.0, 2.0])
    paddle.seed(0)
    rr = np.asarray(F.rrelu(_t(np.full((1000,), -1.0, np.float32)))._value)
    assert (rr <= -1 / 8 + 1e-6).all() and (rr >= -1 / 3 - 1e-6).all()
    ri = np.asarray(F.rrelu(_t(np.asarray([-1.0], np.float32)),
                            training=False)._value)
    np.testing.assert_allclose(ri, [-(1 / 8 + 1 / 3) / 2], rtol=1e-6)


def test_lrn_and_dropout3d():
    x = _t(R.randn(2, 6, 4, 4).astype(np.float32))
    out = F.local_response_norm(x, size=3)
    assert out.shape == x.shape
    # k=1, alpha small -> close to identity
    np.testing.assert_allclose(np.asarray(out._value),
                               np.asarray(x._value), rtol=1e-2, atol=1e-2)
    paddle.seed(1)
    x5 = _t(np.ones((2, 8, 2, 2, 2), np.float32))
    d = np.asarray(F.dropout3d(x5, p=0.5)._value)
    per_channel = d.reshape(2, 8, -1)
    # each channel fully kept (scaled) or fully dropped
    assert all(len(np.unique(per_channel[i, j])) == 1
               for i in range(2) for j in range(8))


@pytest.mark.slow
def test_simple_losses():
    p = _t(np.asarray([0.9, 0.2], np.float32))
    y = _t(np.asarray([1.0, 0.0], np.float32))
    ll = np.asarray(F.log_loss(p, y)._value)
    np.testing.assert_allclose(
        ll, [-np.log(0.9 + 1e-4), -np.log(0.8 + 1e-4)], rtol=1e-4)

    x = _t(R.randn(6).astype(np.float32))
    t = _t(np.sign(R.randn(6)).astype(np.float32))
    sm = float(F.soft_margin_loss(x, t)._value)
    ref = np.log1p(np.exp(-np.asarray(t._value)
                          * np.asarray(x._value))).mean()
    np.testing.assert_allclose(sm, ref, rtol=1e-5)

    a, b, n = (_t(R.randn(4, 8).astype(np.float32)) for _ in range(3))
    tl = float(F.triplet_margin_loss(a, b, n)._value)
    assert np.isfinite(tl) and tl >= 0
    pd = F.pairwise_distance(a, b)
    assert tuple(pd.shape) == (4,)
    ce = float(F.cosine_embedding_loss(
        a, b, _t(np.asarray([1, -1, 1, -1], np.float32)))._value)
    assert np.isfinite(ce)
    fo = float(F.sigmoid_focal_loss(
        x, _t((np.sign(np.asarray(t._value)) > 0)
              .astype(np.float32)))._value)
    assert np.isfinite(fo)
    gn = float(F.gaussian_nll_loss(a, b, _t(np.ones((4, 8),
                                                    np.float32)))._value)
    assert np.isfinite(gn)
    pn = float(F.poisson_nll_loss(a, _t(np.abs(np.asarray(b._value))))._value)
    assert np.isfinite(pn)
    ml = float(F.multi_label_soft_margin_loss(
        a, _t((R.rand(4, 8) > 0.5).astype(np.float32)))._value)
    assert np.isfinite(ml)
    he = float(F.hinge_embedding_loss(
        a, _t(np.sign(R.randn(4, 8)).astype(np.float32)))._value)
    assert np.isfinite(he)
    dl = float(F.dice_loss(
        _t(jax.nn.softmax(R.randn(2, 5, 3).astype(np.float32))),
        _t(R.randint(0, 3, (2, 5, 1)).astype(np.int64)))._value)
    assert 0 <= dl <= 1
    npl = float(F.npair_loss(a, b, _t(np.asarray([0, 1, 0, 1],
                                                 np.int64)))._value)
    assert np.isfinite(npl)


def test_margin_cross_entropy_reduces_to_softmax_at_zero_margin():
    logits = _t((R.rand(4, 6).astype(np.float32) - 0.5))  # in [-0.5, 0.5]
    y = _t(np.asarray([0, 2, 4, 5], np.int64))
    out = float(F.margin_cross_entropy(logits, y, margin1=1.0, margin2=0.0,
                                       margin3=0.0, scale=1.0)._value)
    lf = np.asarray(logits._value)
    ref = -np.take_along_axis(
        np.asarray(jax.nn.log_softmax(lf, axis=-1)),
        np.asarray(y._value, np.int64)[:, None], axis=1).mean()
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def _ctc_bruteforce(log_probs, label, T):
    """Sum over all alignments that collapse to `label`."""
    C = log_probs.shape[-1]
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        # collapse: remove repeats then blanks (blank=0)
        out = []
        prev = None
        for s in path:
            if s != prev:
                out.append(s)
            prev = s
        out = [s for s in out if s != 0]
        if out == list(label):
            lp = sum(log_probs[t, path[t]] for t in range(T))
            total = np.logaddexp(total, lp)
    return -total


def test_ctc_loss_matches_bruteforce():
    T, B, C, L = 4, 2, 3, 2
    paddle.seed(0)
    logits = R.randn(T, B, C).astype(np.float32)
    logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
    labels = np.asarray([[1, 2], [2, 0]], np.int32)  # second: length 1
    ilen = np.asarray([4, 3], np.int32)
    llen = np.asarray([2, 1], np.int32)
    got = np.asarray(F.ctc_loss(_t(logp), _t(labels), _t(ilen), _t(llen),
                                reduction="none")._value)
    ref0 = _ctc_bruteforce(logp[:, 0], [1, 2], 4)
    ref1 = _ctc_bruteforce(logp[:3, 1], [2], 3)
    np.testing.assert_allclose(got, [ref0, ref1], rtol=1e-4)


@pytest.mark.slow
def test_ctc_loss_grad_flows():
    logp = _t(np.asarray(jax.nn.log_softmax(
        R.randn(5, 1, 4).astype(np.float32), axis=-1)))
    logp.stop_gradient = False
    loss = F.ctc_loss(logp, _t(np.asarray([[1, 2]], np.int32)),
                      _t(np.asarray([5], np.int32)),
                      _t(np.asarray([2], np.int32)))
    loss.backward()
    assert logp.grad is not None
    assert np.isfinite(np.asarray(logp.grad._value)).all()


def test_functional_reexports():
    x = _t(R.randn(1, 4, 4, 4).astype(np.float32))
    assert tuple(F.pixel_unshuffle(x, 2).shape) == (1, 16, 2, 2)
    assert tuple(F.channel_shuffle(x, 2).shape) == (1, 4, 4, 4)


def test_conv2d_transpose_matches_scatter_oracle():
    """Round-2 fix: the old transpose_kernel path transposed channel mixing
    and rejected in_c != out_c. Oracle: explicit scatter accumulation."""
    def oracle(x, w, s, p):
        n, ci, H, W_ = x.shape
        _, co, kh, kw = w.shape
        full = np.zeros((n, co, (H - 1) * s + kh, (W_ - 1) * s + kw),
                        np.float32)
        for nn in range(n):
            for i in range(ci):
                for o in range(co):
                    for h in range(H):
                        for ww in range(W_):
                            full[nn, o, h * s:h * s + kh,
                                 ww * s:ww * s + kw] += x[nn, i, h, ww] * w[i, o]
        return full[:, :, p:full.shape[2] - p, p:full.shape[3] - p] \
            if p else full

    x = R.randn(2, 4, 3, 3).astype(np.float32)
    w = R.randn(4, 3, 2, 2).astype(np.float32)
    for s, p in [(1, 0), (2, 0), (2, 1)]:
        got = np.asarray(F.conv2d_transpose(_t(x), _t(w), stride=s,
                                            padding=p)._value)
        np.testing.assert_allclose(got, oracle(x, w, s, p), rtol=1e-4,
                                   atol=1e-5, err_msg=f"s={s} p={p}")


def test_conv2d_transpose_grouped():
    x = R.randn(1, 4, 3, 3).astype(np.float32)
    w = R.randn(4, 2, 2, 2).astype(np.float32)  # groups=2: [in, out/g, k, k]
    got = np.asarray(F.conv2d_transpose(_t(x), _t(w), stride=1,
                                        groups=2)._value)
    # per-group scatter oracle
    full = np.zeros((1, 4, 4, 4), np.float32)
    for g in range(2):
        for i in range(2):
            for o in range(2):
                for h in range(3):
                    for ww in range(3):
                        full[0, g * 2 + o, h:h + 2, ww:ww + 2] += \
                            x[0, g * 2 + i, h, ww] * w[g * 2 + i, o]
    np.testing.assert_allclose(got, full, rtol=1e-4, atol=1e-5)
