"""nan/inf debugging: eager check, dispatch flag, checkify in compiled fns
(SURVEY.md §5.2)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.amp import debugging as D


def test_check_numerics_eager():
    ok = paddle.to_tensor(np.ones(4, np.float32))
    assert D.check_numerics(ok) == (0, 0)
    bad = paddle.to_tensor(np.array([1.0, np.nan, np.inf], np.float32))
    with pytest.raises(FloatingPointError, match="1 nan, 1 inf"):
        D.check_numerics(bad, op_type="test", var_name="x")
    n_nan, n_inf = D.check_numerics(bad, debug_mode=D.DebugMode.CHECK_NAN_INF)
    assert (n_nan, n_inf) == (1, 1)


def test_dispatch_flag_scan():
    cfg = D.TensorCheckerConfig(enable=True)
    D.enable_tensor_checker(cfg)
    try:
        x = paddle.to_tensor(np.zeros(3, np.float32), )
        x.stop_gradient = False
        with pytest.raises(FloatingPointError):
            y = paddle.to_tensor(np.zeros(3, np.float32)) / x  # 0/0 -> nan
    finally:
        D.disable_tensor_checker()


def test_checkify_catches_nan_in_jit():
    def f(x):
        return jnp.log(x).sum()

    wrapped = D.checkify_wrap(f)
    assert float(wrapped(jnp.ones(3))) == 0.0
    with pytest.raises(FloatingPointError, match="log"):
        wrapped(jnp.array([-1.0, 1.0]))


def test_checkify_catches_inf():
    def f(x):
        return (1.0 / x).sum()

    wrapped = D.checkify_wrap(f)
    with pytest.raises(FloatingPointError):
        wrapped(jnp.array([0.0, 1.0]))


def test_dispatch_flag_scan_no_grad_path():
    D.enable_tensor_checker(D.TensorCheckerConfig(enable=True))
    try:
        a = paddle.to_tensor(np.zeros(3, np.float32))  # stop_gradient=True
        with pytest.raises(FloatingPointError):
            a / a
    finally:
        D.disable_tensor_checker()


def test_report_only_mode_does_not_abort():
    D.enable_tensor_checker(D.TensorCheckerConfig(
        enable=True, debug_mode=D.DebugMode.CHECK_NAN_INF))
    try:
        a = paddle.to_tensor(np.zeros(3, np.float32))
        out = a / a  # nan, but report-only: no raise
        assert np.isnan(np.asarray(out._value)).all()
    finally:
        D.disable_tensor_checker()


def test_skipped_op_list():
    D.enable_tensor_checker(D.TensorCheckerConfig(
        enable=True, skipped_op_list=["divide"]))
    try:
        a = paddle.to_tensor(np.zeros(3, np.float32))
        a / a  # divide skipped: no raise
    finally:
        D.disable_tensor_checker()
