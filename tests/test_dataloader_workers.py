"""Multiprocess DataLoader workers: ordering, parity with num_workers=0,
worker failure surfacing, collate in workers (SURVEY.md §2.5 DataLoader)."""

import numpy as np
import pytest

from paddle_tpu import io

pytestmark = pytest.mark.slow  # core tier: -m 'not slow'


class SquareDataset(io.Dataset):
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        return np.asarray([i * i], np.float32)

    def __len__(self):
        return self.n


def test_multiprocess_matches_serial():
    ds = SquareDataset(23)
    serial = [np.asarray(b) for b in
              io.DataLoader(ds, batch_size=4, num_workers=0)]
    parallel = [np.asarray(b) for b in
                io.DataLoader(ds, batch_size=4, num_workers=3)]
    assert len(serial) == len(parallel) == 6
    for a, b in zip(serial, parallel):
        np.testing.assert_array_equal(a, b)


def test_multiprocess_shuffle_epoch():
    ds = SquareDataset(16)
    loader = io.DataLoader(ds, batch_size=4, num_workers=2, shuffle=True)
    vals = np.concatenate([np.asarray(b).ravel() for b in loader])
    assert sorted(vals.tolist()) == [float(i * i) for i in range(16)]


class BoomDataset(io.Dataset):
    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom")
        return np.asarray([i], np.float32)

    def __len__(self):
        return 8


def test_worker_error_propagates():
    loader = io.DataLoader(BoomDataset(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(loader)


def _init_fn(worker_id):
    # runs inside the worker process; assert get_worker_info works there
    # (module-level: spawn-context workers pickle their init_fn)
    info = io.get_worker_info()
    assert info is not None and info.id == worker_id


def test_worker_init_fn_and_info():
    ds = SquareDataset(8)
    out = list(io.DataLoader(ds, batch_size=2, num_workers=2,
                             worker_init_fn=_init_fn))
    assert len(out) == 4
