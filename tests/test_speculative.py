"""Speculative decoding inside the unified ragged step (ISSUE 9):
drafters, verify-in-one-dispatch byte-identity, paged rollback,
O(1) recompiles, telemetry/statusz surfaces."""

import json

import numpy as np
import pytest

from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                           GenerationConfig)
from paddle_tpu.inference.speculative import (DraftModel, Drafter,
                                              NgramDrafter)
from paddle_tpu.models import llama as L
from paddle_tpu.observability import get_registry
from paddle_tpu.observability.events import configure_event_log

CFG = L.llama_tiny(num_hidden_layers=2)
PARAMS = L.init_stacked_params(CFG, seed=0)


def _prompts(n=6, lens=(4, 12), seed=1):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, CFG.vocab_size,
                        (int(rng.randint(*lens)),)).astype(np.int32)
            for _ in range(n)]


def _engine(max_new=16, num_slots=2, page_size=16, max_seq_len=64,
            **kw):
    return ContinuousBatchingEngine(
        CFG, GenerationConfig(max_new_tokens=max_new),
        num_slots=num_slots, page_size=page_size,
        max_seq_len=max_seq_len, chunk=2, **kw)


# ---------------------------------------------------------------------------
# drafters
# ---------------------------------------------------------------------------

def test_ngram_drafter_prompt_lookup():
    d = NgramDrafter(max_ngram=3, min_ngram=1)
    # trailing [7, 8] occurred earlier; continuation is [9, 1, 2, ...]
    assert d.draft([7, 8, 9, 1, 2, 7, 8], 3) == [9, 1, 2]
    # most RECENT earlier occurrence wins (5 follows the later [1, 2])
    assert d.draft([1, 2, 3, 1, 2, 5, 9, 1, 2], 1) == [5]
    # longest n-gram wins over a shorter, more recent match
    assert d.draft([1, 2, 3, 8, 4, 3, 9, 1, 2, 3], 1) == [8]
    # no earlier occurrence of any trailing n-gram: no draft
    assert d.draft([1, 2, 3, 4], 2) == []
    # min_ngram=2 refuses 1-token evidence
    assert NgramDrafter(max_ngram=3, min_ngram=2).draft(
        [5, 1, 2, 3, 5], 2) == []
    assert NgramDrafter(max_ngram=3, min_ngram=1).draft(
        [5, 1, 2, 3, 5], 2) == [1, 2]
    # k caps the proposal; short continuations come back short (the
    # drafter only replays what it has seen — it never extrapolates)
    assert d.draft([4, 4, 4], 2) == [4]
    assert d.draft([1, 9, 1], 5) == [9, 1]
    with pytest.raises(ValueError):
        NgramDrafter(max_ngram=0)


def test_draft_model_hook_drafts_the_small_models_greedy_chain():
    import jax.numpy as jnp
    dm = DraftModel(PARAMS, CFG, window=32)
    hist = [3, 7, 11, 2]
    got = dm.draft(hist, 3)
    assert len(got) == 3
    # oracle: iterative cache-less greedy with forward_stacked
    toks = list(hist)
    for _ in range(3):
        ids = np.zeros((1, 32), np.int32)
        ids[0, :len(toks)] = toks[-32:]
        lg = L.forward_stacked(PARAMS, jnp.asarray(ids), CFG)
        toks.append(int(jnp.argmax(lg[0, len(toks) - 1]
                                   .astype(jnp.float32))))
    assert got == toks[len(hist):]
    # a Drafter (duck-typed) plugs straight into the engine
    eng = _engine(max_new=6, speculative=True, spec_k=2, drafter=dm)
    ref = _engine(max_new=6).serve(PARAMS, _prompts(2))
    assert [list(o) for o in eng.serve(PARAMS, _prompts(2))] == \
        [list(o) for o in ref]
    # self-drafting with the TARGET model accepts heavily: the draft IS
    # the greedy chain (only cross-program/windowing ties may reject)
    st = eng.spec.snapshot()
    assert st["drafted"] > 0 and st["acceptance_ratio"] > 0.8


# ---------------------------------------------------------------------------
# byte-identity: speculative on/off
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefix_cache", [False, True])
def test_spec_byte_identical_greedy(prefix_cache):
    """Greedy output is byte-identical speculative on/off — cache off
    and on (warm suffixes + COW riding the same speculative rounds)."""
    prompts = _prompts(6)
    ref = _engine(prefix_cache=prefix_cache).serve(PARAMS, prompts)
    eng = _engine(prefix_cache=prefix_cache, speculative=True, spec_k=4)
    out = eng.serve(PARAMS, prompts)
    assert [list(o) for o in out] == [list(o) for o in ref]
    assert eng.spec.stats["drafted"] > 0      # speculation actually ran


def test_spec_byte_identical_cow_wave():
    """Full-prompt resubmissions (COW wave): the copy-on-write admission
    path and speculative rounds compose byte-identically."""
    p = _prompts(1, lens=(8, 9))[0]
    wave = [p, p, p[:4], p, p]
    ref = _engine(page_size=4, prefix_cache=True).serve(PARAMS, wave)
    eng = _engine(page_size=4, prefix_cache=True, speculative=True)
    out = eng.serve(PARAMS, wave)
    assert [list(o) for o in out] == [list(o) for o in ref]
    assert eng.cache.stats["cow_copies"] > 0


def test_spec_mid_decode_admission_byte_identical():
    """Requests submitted while other rows are mid-decode join the same
    speculative dispatch; outputs match a fresh non-speculative engine."""
    prompts = _prompts(5)
    ref = _engine().serve(PARAMS, prompts)
    eng = _engine(speculative=True)
    rids = [eng.submit(p) for p in prompts[:2]]
    results = {}
    i = 2
    steps = 0
    while len(results) < len(prompts):
        if i < len(prompts) and steps % 2 == 0:
            rids.append(eng.submit(prompts[i]))
            i += 1
        eng.step(PARAMS)
        results.update(eng.collect())
        steps += 1
        assert steps < 500
    assert [results[r] for r in rids] == [list(o) for o in ref]


def test_spec_eos_retires_identically():
    """An EOS landing inside an accepted draft span retires the row at
    the EOS, exactly like the non-speculative engine."""
    prompts = _prompts(4, seed=7)
    ref_eng = _engine()
    ref_eng.config.eos_token_id = 5
    ref = ref_eng.serve(PARAMS, prompts)
    eng = _engine(speculative=True)
    eng.config.eos_token_id = 5
    out = eng.serve(PARAMS, prompts)
    assert [list(o) for o in out] == [list(o) for o in ref]


# ---------------------------------------------------------------------------
# paged rollback
# ---------------------------------------------------------------------------

class _WrongDrafter(Drafter):
    """Deterministically drafts the WRONG continuation (true greedy
    token + 1 mod vocab) — every draft token is rejected."""

    def __init__(self, refs):
        self.refs = refs    # prompt-key -> full greedy continuation

    def draft(self, history, k):
        for plen, ref in self.refs:
            if history[:plen] == list(map(int, ref["prompt"])):
                done = len(history) - plen
                cont = ref["out"][done:done + k]
                wrong = [(int(t) + 1) % CFG.vocab_size for t in cont]
                # keep drafting past the reference's end so the span
                # always grows the page table before being rejected
                return wrong + [1] * (k - len(wrong))
        raise AssertionError("unknown history")


def test_rejection_rolls_back_and_drafts_never_overdraft(tmp_path):
    """Full rejection every round: the committed length rolls back to
    carry+0 each time, ``spec_rollback`` fires per rejection,
    conservation holds after every step, output is byte-identical —
    and drafts are clamped to the remaining budget (positions past it
    could never commit), so the span always fits the admission
    reservation and rejections strand nothing."""
    p = np.asarray([3, 9, 4, 11], np.int32)   # lp=4, budget=4
    ref = _engine(max_new=4, num_slots=1, page_size=4,
                  max_seq_len=16).serve(PARAMS, [p])
    refs = [(4, {"prompt": p, "out": ref[0]})]
    configure_event_log(str(tmp_path / "ev.jsonl"))
    try:
        eng = _engine(max_new=4, num_slots=1, page_size=4,
                      max_seq_len=16, speculative=True, spec_k=4,
                      drafter=_WrongDrafter(refs))
        out = eng.serve(PARAMS, [p])
    finally:
        configure_event_log(None)
    assert list(out[0]) == list(ref[0])
    st = eng.spec.stats
    assert st["accepted"] == 0 and st["rejected"] == st["drafted"]
    # budget clamp: decode rounds at rem=3/2/1 draft 2/1/0 tokens —
    # never the k=4 the drafter offers
    assert st["drafted"] == 3 and st["rollbacks"] == 2
    assert st["rollback_pages"] == 0      # spans fit the reservation
    events = [json.loads(l) for l in
              (tmp_path / "ev.jsonl").read_text().splitlines()]
    rb = [e for e in events if e["kind"] == "spec_rollback"]
    assert len(rb) == 2
    assert all(e["accepted"] == 0 and e["freed_pages"] == 0 for e in rb)
    # pool fully drained after retire
    assert eng.mgr.num_free_pages == eng.mgr.usable_pages
    eng.mgr.check_conservation()


def test_truncate_frees_stranded_pages_engine_safety_net():
    """The engine's rejection rollback reclaims pages past the
    reservation if an allocation policy ever leaves them (the lazy-
    growth future; forced here by growing a live row's table by hand):
    truncate frees exactly the stranded tail, never below the
    admission reservation, and the books stay balanced."""
    p = np.asarray([3, 9, 4, 11], np.int32)   # lp=4, budget=8, page=4
    ref = _engine(max_new=8, num_slots=1, page_size=4,
                  max_seq_len=16).serve(PARAMS, [p])
    refs = [(4, {"prompt": p, "out": ref[0]})]
    eng = _engine(max_new=8, num_slots=1, page_size=4, max_seq_len=16,
                  speculative=True, spec_k=4, drafter=_WrongDrafter(refs))
    rid = eng.submit(p)
    eng.step(PARAMS)                  # prefill + first sample
    eng.step(PARAMS)                  # one rejected speculative round
    # strand a page past the reservation (pages_for(4+8) = 3)
    eng.mgr.grow_to(rid, 16)
    assert len(eng.mgr._tables[rid]) == 4
    eng.mgr.check_conservation()      # grown-but-uncommitted balances
    eng.step(PARAMS)                  # rejection -> truncate to floor
    assert len(eng.mgr._tables[rid]) == 3
    assert eng.spec.stats["rollback_pages"] == 1
    results = {}
    steps = 0
    while not results:
        eng.step(PARAMS)
        results.update(eng.collect())
        steps += 1
        assert steps < 100
    assert results[rid] == list(ref[0])
    assert eng.mgr.num_free_pages == eng.mgr.usable_pages


def test_pool_pressure_clamps_draft_instead_of_failing():
    """With zero spare pages beyond the admission reservation, grow_to
    raises and the engine shrinks the draft — the round still runs and
    output stays byte-identical."""
    p = np.asarray([3, 9, 4, 11], np.int32)
    ref = _engine(max_new=4, num_slots=1, page_size=4,
                  max_seq_len=16).serve(PARAMS, [p])
    eng = _engine(max_new=4, num_slots=1, page_size=4, max_seq_len=16,
                  num_pages=3,      # usable 2 == reservation exactly
                  speculative=True, spec_k=4)
    out = eng.serve(PARAMS, [p])
    assert list(out[0]) == list(ref[0])
    eng.mgr.check_conservation()


def test_cancel_mid_flight_stays_conserved():
    prompts = _prompts(4)
    eng = _engine(speculative=True, prefix_cache=True)
    rids = [eng.submit(p) for p in prompts]
    eng.step(PARAMS)
    eng.step(PARAMS)
    assert eng.cancel(rids[0])
    eng.step(PARAMS)                 # conservation audited in-step
    while eng.step(PARAMS) or eng.num_queued:
        pass
    done = eng.collect()
    assert rids[0] not in done
    assert set(rids[1:]) <= set(done)


# ---------------------------------------------------------------------------
# O(1) recompiles
# ---------------------------------------------------------------------------

def test_spec_storm_recompiles_o1():
    """Length-diverse storm with mid-decode admissions on a speculative
    engine: ONE compiled program (<= 2 misses tolerated for the flag
    contract), one program object reused for every round."""
    from paddle_tpu.observability.runtime import recompiles
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, CFG.vocab_size,
                           (int(rng.randint(4, 48)),)).astype(np.int32)
               for _ in range(12)]
    eng = ContinuousBatchingEngine(
        CFG, GenerationConfig(max_new_tokens=8), num_slots=4,
        page_size=16, max_seq_len=64, chunk=2, speculative=True)
    rc0 = recompiles.count("cbe.spec_step")
    rids = [eng.submit(p) for p in prompts[:4]]
    prog = None
    i, steps, results = 4, 0, {}
    while len(results) < len(prompts):
        if i < len(prompts) and steps % 2 == 0:
            rids.append(eng.submit(prompts[i]))
            i += 1
        eng.step(PARAMS)
        if prog is None:
            prog = eng._spec_step
        assert eng._spec_step is prog     # never rebuilt
        results.update(eng.collect())
        steps += 1
        assert steps < 2000
    assert recompiles.count("cbe.spec_step") - rc0 <= 2
    assert len(results) == len(prompts)


# ---------------------------------------------------------------------------
# config surface + telemetry
# ---------------------------------------------------------------------------

def test_speculative_requires_unified():
    with pytest.raises(ValueError, match="unified"):
        _engine(speculative=True, unified=False)


def test_speculative_accepts_do_sample():
    """The old hard rejection of do_sample+speculative is gone: the
    rejection-sampling verifier makes sampled speculation lossless, so
    construction succeeds and sampled requests complete."""
    eng = ContinuousBatchingEngine(
        CFG, GenerationConfig(max_new_tokens=4, do_sample=True, seed=3),
        num_slots=2, page_size=16, max_seq_len=64, chunk=2,
        speculative=True)
    rids = [eng.submit(p) for p in _prompts(2)]
    out, steps = {}, 0
    while len(out) < 2:
        eng.step(PARAMS)
        out.update(eng.collect())
        steps += 1
        assert steps < 2000
    assert all(len(out[r]) == 4 for r in rids)


def test_spec_metrics_and_statusz():
    from paddle_tpu.serving import SchedulerConfig, ServingScheduler
    reg = get_registry()
    eng = _engine(speculative=True)
    eng.spec.replica = "7"                # what ReplicaHandle does
    sched = ServingScheduler(eng, SchedulerConfig(max_queue_depth=8))
    for p in _prompts(3):
        sched.submit(p)
    sched.run(PARAMS, max_steps=10_000)
    st = sched.statusz()["speculation"]
    assert st["drafted"] == eng.spec.stats["drafted"] > 0
    assert st["accepted"] == eng.spec.stats["accepted"]
    assert 0.0 <= st["acceptance_ratio"] <= 1.0
    # registry families carry the replica label
    assert eng.spec._c_drafted.value(replica="7") == st["drafted"]
    assert eng.spec._g_ratio.value(replica="7") == pytest.approx(
        st["acceptance_ratio"], abs=1e-4)
    # ... and show up in one valid /metrics exposition
    assert 'paddle_spec_drafted_tokens_total{replica="7"}' in \
        reg.prometheus_text()


def test_replica_handle_stamps_spec_label():
    from paddle_tpu.serving import ReplicaHandle
    eng = _engine(speculative=True)
    ReplicaHandle(3, eng)
    assert eng.spec.replica == "3"
