"""Round-5 API-audit sweep #5: paddle.audio, paddle.text (Viterbi),
paddle.autograd.jacobian + incubate.autograd functional transforms,
paddle.utils (dlpack, unique_name), paddle.onnx shim.

Reference: python/paddle/{audio,text,autograd,utils,onnx}/:§0.
"""

import itertools
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle


class TestAudioFunctional:
    def test_hz_mel_roundtrip(self):
        from paddle_tpu.audio import functional as AF
        for htk in (False, True):
            for f in (60.0, 440.0, 1000.0, 4000.0):
                back = AF.mel_to_hz(AF.hz_to_mel(f, htk=htk), htk=htk)
                assert abs(back - f) < 1e-2 * max(1.0, f / 100)

    def test_htk_formula(self):
        from paddle_tpu.audio import functional as AF
        f = 700.0
        want = 2595.0 * math.log10(2.0)
        assert abs(AF.hz_to_mel(f, htk=True) - want) < 1e-3

    def test_fbank_matrix_properties(self):
        from paddle_tpu.audio import functional as AF
        fb = np.asarray(AF.compute_fbank_matrix(
            16000, 512, n_mels=40)._value)
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # each filter is unimodal triangular: nonzero support is contiguous
        for row in fb:
            nz = np.nonzero(row)[0]
            if len(nz):
                assert (np.diff(nz) == 1).all()

    def test_power_to_db(self):
        from paddle_tpu.audio import functional as AF
        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = np.asarray(AF.power_to_db(x, top_db=None)._value)
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-4)
        db2 = np.asarray(AF.power_to_db(x, top_db=15.0)._value)
        np.testing.assert_allclose(db2, [5.0, 10.0, 20.0], atol=1e-4)

    def test_create_dct_ortho(self):
        from paddle_tpu.audio import functional as AF
        d = np.asarray(AF.create_dct(8, 8)._value)
        # orthonormal: D^T D = I for the square case
        np.testing.assert_allclose(d.T @ d, np.eye(8), atol=1e-5)

    def test_windows(self):
        from paddle_tpu.audio import functional as AF
        for name in ("hann", "hamming", "blackman", "bartlett",
                     ("kaiser", 8.0), ("gaussian", 3.0),
                     ("exponential", None, 2.0), "triang", "bohman"):
            w = np.asarray(AF.get_window(name, 32)._value)
            assert w.shape == (32,) and np.isfinite(w).all()
        # periodic hann of even length: w[k] = sin^2(pi k / N)
        w = np.asarray(AF.get_window("hann", 8)._value)
        k = np.arange(8)
        np.testing.assert_allclose(w, np.sin(np.pi * k / 8) ** 2, atol=1e-6)


class TestAudioFeatures:
    def test_shapes_and_jit(self):
        from paddle_tpu.audio.features import (MFCC, LogMelSpectrogram,
                                               MelSpectrogram, Spectrogram)
        x = paddle.to_tensor(
            np.sin(np.arange(4000) * 0.05).astype(np.float32)[None])
        spec = Spectrogram(n_fft=256)
        assert tuple(spec(x).shape) == (1, 129, 63)
        mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)
        assert tuple(mel(x).shape) == (1, 32, 63)
        logmel = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)
        assert tuple(logmel(x).shape) == (1, 32, 63)
        mfcc = MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)
        assert tuple(mfcc(x).shape) == (1, 13, 63)

        # the whole pipeline traces under jit
        f = jax.jit(lambda v: mfcc(paddle.to_tensor(v))._value)
        np.testing.assert_allclose(np.asarray(f(x._value)),
                                   np.asarray(mfcc(x)._value),
                                   rtol=2e-4, atol=2e-4)

    def test_mel_matches_manual_pipeline(self):
        from paddle_tpu.audio import functional as AF
        from paddle_tpu.audio.features import MelSpectrogram
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(1, 2000).astype(np.float32))
        mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=20, power=2.0)
        got = np.asarray(mel(x)._value)
        spec = paddle.signal.stft(
            x, 256, hop_length=64, window=AF.get_window("hann", 256))
        pow_spec = np.abs(np.asarray(spec._value)) ** 2
        fb = np.asarray(AF.compute_fbank_matrix(
            8000, 256, n_mels=20, f_min=50.0)._value)
        want = fb @ pow_spec[0]
        np.testing.assert_allclose(got[0], want, rtol=1e-3, atol=1e-3)


class TestAudioBackends:
    def test_wav_roundtrip(self, tmp_path):
        from paddle_tpu.audio import backends
        x = paddle.to_tensor(
            (0.5 * np.sin(np.arange(800) * 0.1)).astype(np.float32)[None])
        p = str(tmp_path / "t.wav")
        backends.save(p, x, 8000)
        w, sr = backends.load(p)
        assert sr == 8000 and tuple(w.shape) == (1, 800)
        np.testing.assert_allclose(np.asarray(w._value),
                                   np.asarray(x._value), atol=1e-4)
        meta = backends.info(p)
        assert meta.sample_rate == 8000 and meta.num_frames == 800
        assert meta.bits_per_sample == 16


class TestViterbi:
    def _brute(self, emis, trans, L, bos_eos):
        C = trans.shape[0]
        best, bp = -1e18, None
        for seq in itertools.product(range(C), repeat=int(L)):
            s = emis[0, seq[0]] + (trans[C - 2, seq[0]] if bos_eos else 0.0)
            for t in range(1, L):
                s += trans[seq[t - 1], seq[t]] + emis[t, seq[t]]
            if bos_eos:
                s += trans[seq[-1], C - 1]
            if s > best:
                best, bp = s, seq
        return best, list(bp)

    @pytest.mark.parametrize("bos_eos", [True, False])
    def test_matches_brute_force(self, bos_eos):
        from paddle_tpu.text import viterbi_decode
        rs = np.random.RandomState(1)
        B, T, C = 3, 5, 4
        emis = rs.randn(B, T, C).astype(np.float32)
        trans = rs.randn(C, C).astype(np.float32)
        lens = np.array([5, 3, 1], np.int32)
        scores, paths = viterbi_decode(
            paddle.to_tensor(emis), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=bos_eos)
        for b in range(B):
            want_s, want_p = self._brute(emis[b], trans, lens[b], bos_eos)
            assert abs(float(np.asarray(scores._value)[b]) - want_s) < 1e-4
            got_p = list(np.asarray(paths._value)[b][:lens[b]])
            assert got_p == want_p
            # padding zeroed
            assert (np.asarray(paths._value)[b][lens[b]:] == 0).all()

    def test_layer_form_and_jit(self):
        from paddle_tpu.text import ViterbiDecoder
        rs = np.random.RandomState(2)
        emis = rs.randn(2, 4, 5).astype(np.float32)
        trans = rs.randn(5, 5).astype(np.float32)
        lens = np.array([4, 4], np.int32)
        dec = ViterbiDecoder(paddle.to_tensor(trans))
        s1, p1 = dec(paddle.to_tensor(emis), paddle.to_tensor(lens))

        f = jax.jit(lambda e, t, n: tuple(
            o._value for o in dec(paddle.to_tensor(e), paddle.to_tensor(n))))
        s2, p2 = f(emis, trans, lens)
        np.testing.assert_allclose(np.asarray(s1._value), np.asarray(s2),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(p1._value), np.asarray(p2))


class TestAutogradJacobian:
    def test_basic(self):
        from paddle_tpu.autograd import jacobian
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                             stop_gradient=False)
        y = x * x
        J = np.asarray(jacobian(y, x)._value)
        np.testing.assert_allclose(J, np.diag([2.0, 4.0, 6.0]), atol=1e-5)

    def test_nondiag_and_multi_xs(self):
        from paddle_tpu.autograd import jacobian
        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        b = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        y = paddle.concat([a.sum().reshape([1]) * b, a * 2.0])
        Ja, Jb = jacobian(y, [a, b])
        np.testing.assert_allclose(np.asarray(Ja._value),
                                   [[3.0, 3.0], [2.0, 0.0], [0.0, 2.0]],
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(Jb._value),
                                   [[3.0], [0.0], [0.0]], atol=1e-5)

    def test_batch_axis(self):
        from paddle_tpu.autograd import jacobian
        rs = np.random.RandomState(0)
        W = rs.randn(3, 2).astype(np.float32)
        x = paddle.to_tensor(rs.randn(4, 3).astype(np.float32),
                             stop_gradient=False)
        y = paddle.matmul(x, paddle.to_tensor(W))
        J = np.asarray(jacobian(y, x, batch_axis=0)._value)
        assert J.shape == (4, 2, 3)
        for bidx in range(4):
            np.testing.assert_allclose(J[bidx], W.T, atol=1e-5)

    def test_hessian_raises_with_pointer(self):
        from paddle_tpu.autograd import hessian
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        y = (x * x).sum()
        with pytest.raises(NotImplementedError, match="incubate"):
            hessian(y, x)


class TestIncubateAutograd:
    def test_jvp_vjp(self):
        from paddle_tpu.incubate.autograd import jvp, vjp
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out, tan = jvp(lambda v: v * v, x)
        np.testing.assert_allclose(np.asarray(tan._value), [2.0, 4.0])
        out, g = vjp(lambda v: (v ** 3).sum(), x)
        np.testing.assert_allclose(np.asarray(g._value), [3.0, 12.0])

    def test_jacobian_hessian(self):
        from paddle_tpu.incubate.autograd import Hessian, Jacobian
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        J = Jacobian(lambda v: v * v, x)
        np.testing.assert_allclose(np.asarray(J[:]._value),
                                   np.diag([2.0, 4.0, 6.0]), atol=1e-5)
        H = Hessian(lambda v: (v ** 3).sum(), x)
        np.testing.assert_allclose(np.asarray(H[:]._value),
                                   np.diag([6.0, 12.0, 18.0]), atol=1e-5)

    def test_batched_hessian(self):
        from paddle_tpu.incubate.autograd import Hessian
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(3, 2).astype(np.float32))
        H = Hessian(lambda v: (v ** 2).sum(), x, is_batched=True)
        got = np.asarray(H[:]._value)
        assert got.shape == (3, 2, 2)
        for b in range(3):
            np.testing.assert_allclose(got[b], 2.0 * np.eye(2), atol=1e-5)


class TestUtils:
    def test_dlpack_roundtrip(self):
        from paddle_tpu.utils import dlpack
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        cap = dlpack.to_dlpack(x)
        y = dlpack.from_dlpack(cap)
        np.testing.assert_array_equal(np.asarray(y._value),
                                      np.asarray(x._value))

    def test_dlpack_from_numpy_and_torch(self):
        from paddle_tpu.utils import dlpack
        y = dlpack.from_dlpack(np.arange(4).astype(np.float32))
        np.testing.assert_array_equal(np.asarray(y._value), [0, 1, 2, 3])
        torch = pytest.importorskip("torch")
        t = torch.arange(4, dtype=torch.float32)
        z = dlpack.from_dlpack(t)
        np.testing.assert_array_equal(np.asarray(z._value), [0, 1, 2, 3])

    def test_unique_name(self):
        from paddle_tpu.utils import unique_name
        a = unique_name.generate("layer")
        b = unique_name.generate("layer")
        assert a != b and a.startswith("layer_")
        with unique_name.guard():
            c = unique_name.generate("layer")
            assert c == "layer_0"
        d = unique_name.generate("layer")
        assert d != c or d.startswith("layer_")

    def test_try_import_and_deprecated(self):
        from paddle_tpu.utils import deprecated, try_import
        assert try_import("math") is math
        with pytest.raises(ImportError, match="not installed"):
            try_import("definitely_not_a_module_xyz")

        @deprecated(update_to="paddle.new_api", since="2.0")
        def old():
            return 42

        with pytest.warns(DeprecationWarning, match="new_api"):
            assert old() == 42

    def test_run_check(self, capsys):
        paddle.utils.run_check()
        assert "successfully" in capsys.readouterr().out


class TestOnnxShim:
    def test_export_raises_actionable(self):
        with pytest.raises(ImportError, match="jit.save"):
            paddle.onnx.export(None, "/tmp/x")
