"""Round-5 API-audit sweep #5: paddle.audio, paddle.text (Viterbi),
paddle.autograd.jacobian + incubate.autograd functional transforms,
paddle.utils (dlpack, unique_name), paddle.onnx shim.

Reference: python/paddle/{audio,text,autograd,utils,onnx}/:§0.
"""

import itertools
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle


class TestAudioFunctional:
    def test_hz_mel_roundtrip(self):
        from paddle_tpu.audio import functional as AF
        for htk in (False, True):
            for f in (60.0, 440.0, 1000.0, 4000.0):
                back = AF.mel_to_hz(AF.hz_to_mel(f, htk=htk), htk=htk)
                assert abs(back - f) < 1e-2 * max(1.0, f / 100)

    def test_htk_formula(self):
        from paddle_tpu.audio import functional as AF
        f = 700.0
        want = 2595.0 * math.log10(2.0)
        assert abs(AF.hz_to_mel(f, htk=True) - want) < 1e-3

    def test_fbank_matrix_properties(self):
        from paddle_tpu.audio import functional as AF
        fb = np.asarray(AF.compute_fbank_matrix(
            16000, 512, n_mels=40)._value)
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # each filter is unimodal triangular: nonzero support is contiguous
        for row in fb:
            nz = np.nonzero(row)[0]
            if len(nz):
                assert (np.diff(nz) == 1).all()

    def test_power_to_db(self):
        from paddle_tpu.audio import functional as AF
        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = np.asarray(AF.power_to_db(x, top_db=None)._value)
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-4)
        db2 = np.asarray(AF.power_to_db(x, top_db=15.0)._value)
        np.testing.assert_allclose(db2, [5.0, 10.0, 20.0], atol=1e-4)

    def test_create_dct_ortho(self):
        from paddle_tpu.audio import functional as AF
        d = np.asarray(AF.create_dct(8, 8)._value)
        # orthonormal: D^T D = I for the square case
        np.testing.assert_allclose(d.T @ d, np.eye(8), atol=1e-5)

    def test_windows(self):
        from paddle_tpu.audio import functional as AF
        for name in ("hann", "hamming", "blackman", "bartlett",
                     ("kaiser", 8.0), ("gaussian", 3.0),
                     ("exponential", None, 2.0), "triang", "bohman"):
            w = np.asarray(AF.get_window(name, 32)._value)
            assert w.shape == (32,) and np.isfinite(w).all()
        # periodic hann of even length: w[k] = sin^2(pi k / N)
        w = np.asarray(AF.get_window("hann", 8)._value)
        k = np.arange(8)
        np.testing.assert_allclose(w, np.sin(np.pi * k / 8) ** 2, atol=1e-6)


class TestAudioFeatures:
    def test_shapes_and_jit(self):
        from paddle_tpu.audio.features import (MFCC, LogMelSpectrogram,
                                               MelSpectrogram, Spectrogram)
        x = paddle.to_tensor(
            np.sin(np.arange(4000) * 0.05).astype(np.float32)[None])
        spec = Spectrogram(n_fft=256)
        assert tuple(spec(x).shape) == (1, 129, 63)
        mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)
        assert tuple(mel(x).shape) == (1, 32, 63)
        logmel = LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)
        assert tuple(logmel(x).shape) == (1, 32, 63)
        mfcc = MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)
        assert tuple(mfcc(x).shape) == (1, 13, 63)

        # the whole pipeline traces under jit
        f = jax.jit(lambda v: mfcc(paddle.to_tensor(v))._value)
        np.testing.assert_allclose(np.asarray(f(x._value)),
                                   np.asarray(mfcc(x)._value),
                                   rtol=2e-4, atol=2e-4)

    def test_mel_matches_manual_pipeline(self):
        from paddle_tpu.audio import functional as AF
        from paddle_tpu.audio.features import MelSpectrogram
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(1, 2000).astype(np.float32))
        mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=20, power=2.0)
        got = np.asarray(mel(x)._value)
        spec = paddle.signal.stft(
            x, 256, hop_length=64, window=AF.get_window("hann", 256))
        pow_spec = np.abs(np.asarray(spec._value)) ** 2
        fb = np.asarray(AF.compute_fbank_matrix(
            8000, 256, n_mels=20, f_min=50.0)._value)
        want = fb @ pow_spec[0]
        np.testing.assert_allclose(got[0], want, rtol=1e-3, atol=1e-3)


class TestAudioBackends:
    def test_wav_roundtrip(self, tmp_path):
        from paddle_tpu.audio import backends
        x = paddle.to_tensor(
            (0.5 * np.sin(np.arange(800) * 0.1)).astype(np.float32)[None])
        p = str(tmp_path / "t.wav")
        backends.save(p, x, 8000)
        w, sr = backends.load(p)
        assert sr == 8000 and tuple(w.shape) == (1, 800)
        np.testing.assert_allclose(np.asarray(w._value),
                                   np.asarray(x._value), atol=1e-4)
        meta = backends.info(p)
        assert meta.sample_rate == 8000 and meta.num_frames == 800
        assert meta.bits_per_sample == 16


class TestViterbi:
    def _brute(self, emis, trans, L, bos_eos):
        C = trans.shape[0]
        best, bp = -1e18, None
        for seq in itertools.product(range(C), repeat=int(L)):
            s = emis[0, seq[0]] + (trans[C - 2, seq[0]] if bos_eos else 0.0)
            for t in range(1, L):
                s += trans[seq[t - 1], seq[t]] + emis[t, seq[t]]
            if bos_eos:
                s += trans[seq[-1], C - 1]
            if s > best:
                best, bp = s, seq
        return best, list(bp)

    @pytest.mark.parametrize("bos_eos", [True, False])
    def test_matches_brute_force(self, bos_eos):
        from paddle_tpu.text import viterbi_decode
        rs = np.random.RandomState(1)
        B, T, C = 3, 5, 4
        emis = rs.randn(B, T, C).astype(np.float32)
        trans = rs.randn(C, C).astype(np.float32)
        lens = np.array([5, 3, 1], np.int32)
        scores, paths = viterbi_decode(
            paddle.to_tensor(emis), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=bos_eos)
        for b in range(B):
            want_s, want_p = self._brute(emis[b], trans, lens[b], bos_eos)
            assert abs(float(np.asarray(scores._value)[b]) - want_s) < 1e-4
            got_p = list(np.asarray(paths._value)[b][:lens[b]])
            assert got_p == want_p
            # padding zeroed
            assert (np.asarray(paths._value)[b][lens[b]:] == 0).all()

    def test_layer_form_and_jit(self):
        from paddle_tpu.text import ViterbiDecoder
        rs = np.random.RandomState(2)
        emis = rs.randn(2, 4, 5).astype(np.float32)
        trans = rs.randn(5, 5).astype(np.float32)
        lens = np.array([4, 4], np.int32)
        dec = ViterbiDecoder(paddle.to_tensor(trans))
        s1, p1 = dec(paddle.to_tensor(emis), paddle.to_tensor(lens))

        f = jax.jit(lambda e, t, n: tuple(
            o._value for o in dec(paddle.to_tensor(e), paddle.to_tensor(n))))
        s2, p2 = f(emis, trans, lens)
        np.testing.assert_allclose(np.asarray(s1._value), np.asarray(s2),
                                   rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(p1._value), np.asarray(p2))


class TestAutogradJacobian:
    def test_basic(self):
        from paddle_tpu.autograd import jacobian
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                             stop_gradient=False)
        y = x * x
        J = np.asarray(jacobian(y, x)._value)
        np.testing.assert_allclose(J, np.diag([2.0, 4.0, 6.0]), atol=1e-5)

    def test_nondiag_and_multi_xs(self):
        from paddle_tpu.autograd import jacobian
        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        b = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        y = paddle.concat([a.sum().reshape([1]) * b, a * 2.0])
        Ja, Jb = jacobian(y, [a, b])
        np.testing.assert_allclose(np.asarray(Ja._value),
                                   [[3.0, 3.0], [2.0, 0.0], [0.0, 2.0]],
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(Jb._value),
                                   [[3.0], [0.0], [0.0]], atol=1e-5)

    def test_batch_axis(self):
        from paddle_tpu.autograd import jacobian
        rs = np.random.RandomState(0)
        W = rs.randn(3, 2).astype(np.float32)
        x = paddle.to_tensor(rs.randn(4, 3).astype(np.float32),
                             stop_gradient=False)
        y = paddle.matmul(x, paddle.to_tensor(W))
        J = np.asarray(jacobian(y, x, batch_axis=0)._value)
        assert J.shape == (4, 2, 3)
        for bidx in range(4):
            np.testing.assert_allclose(J[bidx], W.T, atol=1e-5)

    def test_hessian_raises_with_pointer(self):
        from paddle_tpu.autograd import hessian
        x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        y = (x * x).sum()
        with pytest.raises(NotImplementedError, match="incubate"):
            hessian(y, x)


class TestIncubateAutograd:
    def test_jvp_vjp(self):
        from paddle_tpu.incubate.autograd import jvp, vjp
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out, tan = jvp(lambda v: v * v, x)
        np.testing.assert_allclose(np.asarray(tan._value), [2.0, 4.0])
        out, g = vjp(lambda v: (v ** 3).sum(), x)
        np.testing.assert_allclose(np.asarray(g._value), [3.0, 12.0])

    def test_jacobian_hessian(self):
        from paddle_tpu.incubate.autograd import Hessian, Jacobian
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        J = Jacobian(lambda v: v * v, x)
        np.testing.assert_allclose(np.asarray(J[:]._value),
                                   np.diag([2.0, 4.0, 6.0]), atol=1e-5)
        H = Hessian(lambda v: (v ** 3).sum(), x)
        np.testing.assert_allclose(np.asarray(H[:]._value),
                                   np.diag([6.0, 12.0, 18.0]), atol=1e-5)

    def test_batched_hessian(self):
        from paddle_tpu.incubate.autograd import Hessian
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(3, 2).astype(np.float32))
        H = Hessian(lambda v: (v ** 2).sum(), x, is_batched=True)
        got = np.asarray(H[:]._value)
        assert got.shape == (3, 2, 2)
        for b in range(3):
            np.testing.assert_allclose(got[b], 2.0 * np.eye(2), atol=1e-5)


class TestUtils:
    def test_dlpack_roundtrip(self):
        from paddle_tpu.utils import dlpack
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        cap = dlpack.to_dlpack(x)
        y = dlpack.from_dlpack(cap)
        np.testing.assert_array_equal(np.asarray(y._value),
                                      np.asarray(x._value))

    def test_dlpack_from_numpy_and_torch(self):
        from paddle_tpu.utils import dlpack
        y = dlpack.from_dlpack(np.arange(4).astype(np.float32))
        np.testing.assert_array_equal(np.asarray(y._value), [0, 1, 2, 3])
        torch = pytest.importorskip("torch")
        t = torch.arange(4, dtype=torch.float32)
        z = dlpack.from_dlpack(t)
        np.testing.assert_array_equal(np.asarray(z._value), [0, 1, 2, 3])

    def test_unique_name(self):
        from paddle_tpu.utils import unique_name
        a = unique_name.generate("layer")
        b = unique_name.generate("layer")
        assert a != b and a.startswith("layer_")
        with unique_name.guard():
            c = unique_name.generate("layer")
            assert c == "layer_0"
        d = unique_name.generate("layer")
        assert d != c or d.startswith("layer_")

    def test_try_import_and_deprecated(self):
        from paddle_tpu.utils import deprecated, try_import
        assert try_import("math") is math
        with pytest.raises(ImportError, match="not installed"):
            try_import("definitely_not_a_module_xyz")

        @deprecated(update_to="paddle.new_api", since="2.0")
        def old():
            return 42

        with pytest.warns(DeprecationWarning, match="new_api"):
            assert old() == 42

    def test_run_check(self, capsys):
        paddle.utils.run_check()
        assert "successfully" in capsys.readouterr().out


class TestOnnxShim:
    def test_export_raises_actionable(self):
        with pytest.raises(ImportError, match="jit.save"):
            paddle.onnx.export(None, "/tmp/x")


class TestDeviceNamespace:
    def test_queries(self):
        assert paddle.device.get_device().startswith(("cpu", "tpu", "axon"))
        assert paddle.device.get_device_count() >= 1
        assert paddle.device.cuda.device_count() == 0
        assert paddle.device.is_compiled_with_cuda() is False
        assert paddle.device.is_compiled_with_distribute() is True
        assert "cpu" in paddle.device.get_all_device_type()
        paddle.device.synchronize()  # no-throw


class TestRegularizer:
    def test_l2_decay_feeds_optimizer(self):
        from paddle_tpu import optimizer
        net = paddle.nn.Linear(4, 4)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=net.parameters(),
                              weight_decay=paddle.regularizer.L2Decay(0.01))
        assert opt._weight_decay == 0.01

    def test_l1_decay_carries_coeff(self):
        r = paddle.regularizer.L1Decay(0.5)
        assert r.coeff == 0.5 and "L1Decay" in repr(r)


class TestCallbacksAndVersion:
    def test_callbacks_reexported(self):
        assert paddle.callbacks.EarlyStopping is not None
        assert paddle.callbacks.ModelCheckpoint is not None

    def test_version(self, capsys):
        assert paddle.version.full_version == paddle.__version__
        paddle.version.show()
        assert "full_version" in capsys.readouterr().out
        assert paddle.version.cuda() == "False"


class TestStaticNN:
    def test_fc_param_reuse(self):
        import paddle_tpu.static as st
        st.nn.static_param_store().clear()
        x = paddle.to_tensor(np.ones((2, 6), np.float32))
        a = st.nn.fc(x, 3, name="shared")
        b = st.nn.fc(x, 3, name="shared")
        np.testing.assert_array_equal(np.asarray(a._value),
                                      np.asarray(b._value))
        assert len(st.nn.static_param_store()) == 1

    def test_builders_shapes(self):
        import paddle_tpu.static as st
        st.nn.static_param_store().clear()
        rs = np.random.RandomState(0)
        img = paddle.to_tensor(rs.randn(2, 3, 8, 8).astype(np.float32))
        assert tuple(st.nn.conv2d(img, 4, 3).shape) == (2, 4, 6, 6)
        assert tuple(st.nn.batch_norm(img).shape) == (2, 3, 8, 8)
        assert tuple(st.nn.layer_norm(img, begin_norm_axis=2).shape) \
            == (2, 3, 8, 8)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int32))
        assert tuple(st.nn.embedding(ids, (10, 5)).shape) == (2, 2, 5)
        assert tuple(st.nn.prelu(img, mode="channel").shape) == (2, 3, 8, 8)

    def test_control_flow_traced(self):
        import jax
        import paddle_tpu.static as st

        def f(x):
            big = st.nn.cond(x.sum() > 3.0, lambda: x * 10.0,
                             lambda: x * -1.0)
            i, acc = st.nn.while_loop(
                lambda i, acc: i < 3,
                lambda i, acc: (i + 1, acc + big.sum()),
                [paddle.to_tensor(0), paddle.to_tensor(0.0)])
            return acc._value

        got = jax.jit(lambda v: f(paddle.to_tensor(v)))(
            np.ones(4, np.float32))
        np.testing.assert_allclose(np.asarray(got), 120.0)
        got2 = jax.jit(lambda v: f(paddle.to_tensor(v)))(
            np.ones(2, np.float32))
        np.testing.assert_allclose(np.asarray(got2), -6.0)

    def test_switch_case_and_case(self):
        import paddle_tpu.static as st
        r = st.nn.switch_case(1, [lambda: paddle.to_tensor(5.0),
                                  lambda: paddle.to_tensor(7.0)])
        assert float(r._value) == 7.0
        r2 = st.nn.case([(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0)),
                         (paddle.to_tensor(True), lambda: paddle.to_tensor(2.0))],
                        default=lambda: paddle.to_tensor(3.0))
        assert float(r2._value) == 2.0


class TestNNUtils:
    def test_weight_norm_roundtrip_and_grads(self):
        from paddle_tpu.nn import utils as U
        lin = paddle.nn.Linear(4, 3)
        w0 = np.asarray(lin.weight._value).copy()
        U.weight_norm(lin, "weight", dim=0)
        np.testing.assert_allclose(np.asarray(lin.weight._value), w0,
                                   rtol=1e-5)
        names = [n for n, _ in lin.named_parameters()]
        assert "weight_g" in names and "weight_v" in names \
            and "weight" not in names
        loss = (lin(paddle.to_tensor(
            np.ones((2, 4), np.float32))) ** 2).sum()
        loss.backward()
        assert lin.weight_g.grad is not None
        assert lin.weight_v.grad is not None
        U.remove_weight_norm(lin, "weight")
        np.testing.assert_allclose(np.asarray(lin.weight._value), w0,
                                   rtol=1e-5)
        assert "weight" in [n for n, _ in lin.named_parameters()]

    def test_weight_norm_trains_compiled(self):
        from paddle_tpu import optimizer
        from paddle_tpu.nn import utils as U
        net = paddle.nn.Linear(4, 2)
        U.weight_norm(net, "weight")
        opt = optimizer.AdamW(learning_rate=1e-2,
                              parameters=net.parameters())
        step = paddle.jit.TrainStep(
            net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(8, 4).astype(np.float32))
        y = paddle.to_tensor(rs.randn(8, 2).astype(np.float32))
        l0 = float(step(x, y)._value)
        for _ in range(15):
            l1 = float(step(x, y)._value)
        assert l1 < l0

    def test_spectral_norm_unit_sigma(self):
        from paddle_tpu.nn import utils as U
        lin = paddle.nn.Linear(8, 8)
        U.spectral_norm(lin, "weight", n_power_iterations=5)
        out = lin(paddle.to_tensor(np.ones((1, 8), np.float32)))
        s = np.linalg.svd(np.asarray(lin.weight._value),
                          compute_uv=False)
        assert abs(s[0] - 1.0) < 0.05
        (out ** 2).sum().backward()
        assert lin.weight_orig.grad is not None

    def test_clip_grad_norm_and_value(self):
        from paddle_tpu.nn import utils as U
        p = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        (p * p * 50).sum().backward()
        total = U.clip_grad_norm_([p], max_norm=1.0)
        assert float(total._value) > 1.0
        assert abs(np.linalg.norm(np.asarray(p.grad._value)) - 1.0) < 1e-4
        q = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
        (q * 10).sum().backward()
        U.clip_grad_value_([q], 0.5)
        np.testing.assert_allclose(np.asarray(q.grad._value), [0.5, 0.5])

    def test_vector_roundtrip(self):
        from paddle_tpu.nn import utils as U
        net = paddle.nn.Linear(3, 2)
        vec = U.parameters_to_vector(net.parameters())
        assert tuple(vec.shape) == (3 * 2 + 2,)
        vals = [np.asarray(p._value).copy() for p in net.parameters()]
        U.vector_to_parameters(vec * 2.0, net.parameters())
        for p, v in zip(net.parameters(), vals):
            np.testing.assert_allclose(np.asarray(p._value), v * 2.0,
                                       rtol=1e-6)
        with pytest.raises(ValueError, match="length"):
            U.vector_to_parameters(
                paddle.to_tensor(np.ones(3, np.float32)),
                net.parameters())


class TestReviewR5Fixes:
    def test_weight_readable_after_compiled_step(self):
        """Review: the weight-norm hook must not leak a tracer into the
        layer's weight cache when forward runs under jit."""
        from paddle_tpu import optimizer
        from paddle_tpu.nn import utils as U
        net = paddle.nn.Linear(4, 2)
        U.weight_norm(net, "weight")
        opt = optimizer.AdamW(learning_rate=1e-2,
                              parameters=net.parameters())
        step = paddle.jit.TrainStep(
            net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
        rs = np.random.RandomState(0)
        step(paddle.to_tensor(rs.randn(8, 4).astype(np.float32)),
             paddle.to_tensor(rs.randn(8, 2).astype(np.float32)))
        w = np.asarray(net.weight._value)   # raised TracerArrayConversion
        assert w.shape == (4, 2)

    def test_spectral_norm_zero_iterations(self):
        from paddle_tpu.nn import utils as U
        lin = paddle.nn.Linear(6, 6)
        U.spectral_norm(lin, "weight", n_power_iterations=0)
        out = lin(paddle.to_tensor(np.ones((1, 6), np.float32)))
        assert np.isfinite(np.asarray(out._value)).all()

    def test_destroy_subgroup_keeps_world(self):
        import paddle_tpu.distributed as dist
        dist.init_parallel_env()
        g = dist.new_group(ranks=[0])
        dist.destroy_process_group(g)
        assert dist.is_initialized()
        dist.destroy_process_group()
        assert not dist.is_initialized()

    def test_multi_step_cached_per_k(self):
        from paddle_tpu import optimizer
        net = paddle.nn.Linear(4, 1)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters())
        step = paddle.jit.TrainStep(net, lambda m, x: m(x).sum(), opt)
        assert step.multi_step(2) is step.multi_step(2)
        assert step.multi_step(3) is not step.multi_step(2)

    def test_static_nn_unnamed_creates_fresh(self):
        """Documented reference semantics: unnamed builder calls create
        new parameters (named calls share — tested above)."""
        import paddle_tpu.static as st
        st.nn.static_param_store().clear()
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        st.nn.fc(x, 2)
        st.nn.fc(x, 2)
        assert len(st.nn.static_param_store()) == 2


class TestHub:
    def test_local_hubconf(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny(out_features=2):\n"
            "    'A tiny linear model.'\n"
            "    import paddle_tpu as paddle\n"
            "    return paddle.nn.Linear(4, out_features)\n")
        names = paddle.hub.list(str(tmp_path))
        assert "tiny" in names
        assert "tiny linear" in paddle.hub.help(str(tmp_path), "tiny")
        m = paddle.hub.load(str(tmp_path), "tiny", out_features=3)
        assert tuple(m(paddle.to_tensor(
            np.ones((1, 4), np.float32))).shape) == (1, 3)

    def test_remote_sources_refused(self):
        with pytest.raises(RuntimeError, match="network"):
            paddle.hub.list("user/repo", source="github")

    def test_weight_norm_dim1_size1_roundtrip(self):
        """Review r5: remove_weight_norm must use the RECORDED dim, not
        re-infer it (size-1 normed axes mis-inferred)."""
        from paddle_tpu.nn import utils as U
        lin = paddle.nn.Linear(4, 1)
        w0 = np.asarray(lin.weight._value).copy()
        U.weight_norm(lin, "weight", dim=1)
        U.remove_weight_norm(lin, "weight")
        np.testing.assert_allclose(np.asarray(lin.weight._value), w0,
                                   rtol=1e-5, atol=1e-7)


class TestGradHooksAndAliases:
    def test_register_hook_observe_and_replace(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        seen = {}
        x.register_hook(lambda g: seen.setdefault(
            "g", np.asarray(g._value)))
        (x * 3.0).sum().backward()
        np.testing.assert_allclose(seen["g"], [3.0, 3.0])
        np.testing.assert_allclose(np.asarray(x.grad._value), [3.0, 3.0])

        y = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        y.register_hook(lambda g: g * 10.0)
        (y * 2.0).sum().backward()
        np.testing.assert_allclose(np.asarray(y.grad._value), [20.0])

    def test_register_hook_intermediate_and_remove(self):
        a = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        b = a * 3.0
        b.register_hook(lambda g: g * 100.0)
        (b * 1.0).sum().backward()
        np.testing.assert_allclose(np.asarray(a.grad._value), [300.0])

        c = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        h = c.register_hook(lambda g: g * 5.0)
        h.remove()
        (c * 2.0).sum().backward()
        np.testing.assert_allclose(np.asarray(c.grad._value), [2.0])

    def test_register_hook_requires_grad(self):
        t = paddle.to_tensor(np.ones(2, np.float32))
        with pytest.raises(RuntimeError, match="stop_gradient"):
            t.register_hook(lambda g: g)

    def test_namespace_aliases(self):
        import paddle_tpu.distributed.fleet as fleet
        import paddle_tpu.nn as nn
        assert nn.quant.weight_only_linear is not None
        assert nn.quant.weight_quantize is not None
        assert callable(fleet.utils.recompute)

    def test_hook_fires_once_with_accumulated_grad(self):
        """Review r5: a multi-use tensor's hook gets the ACCUMULATED
        gradient once, not per-edge partials."""
        x = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        calls = []
        x.register_hook(lambda g: calls.append(np.asarray(g._value)))
        (x * 2.0 + x * 3.0).sum().backward()
        assert len(calls) == 1
        np.testing.assert_allclose(calls[0], [5.0])
        # non-linear hook sees the total (clip(5)=4, not clip2+clip3=5)
        y = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        y.register_hook(lambda g: g.clip(max=4.0))
        (y * 2.0 + y * 3.0).sum().backward()
        np.testing.assert_allclose(np.asarray(y.grad._value), [4.0])

    def test_hook_on_backward_root_fires_with_seed(self):
        a = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        b = a * 2.0
        b.register_hook(lambda g: g * 10.0)
        b.backward()
        np.testing.assert_allclose(np.asarray(a.grad._value), [20.0])

    def test_stale_handle_remove_is_noop(self):
        t = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        h1 = t.register_hook(lambda g: g)
        h1.remove()
        t.register_hook(lambda g: g * 7.0)
        h1.remove()   # must not delete the newer hook
        (t * 1.0).sum().backward()
        np.testing.assert_allclose(np.asarray(t.grad._value), [7.0])

    def test_hook_with_paddle_grad_capture(self):
        from paddle_tpu.autograd import grad
        q = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        q.register_hook(lambda g: g * 10.0)
        (gq,) = grad((q * 3.0).sum(), q)
        np.testing.assert_allclose(np.asarray(gq._value), [30.0])


class TestTopLevelModeAPIs:
    def test_paddle_grad_top_level(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        (g,) = paddle.grad((x * x).sum(), x)
        np.testing.assert_allclose(np.asarray(g._value), [2.0, 4.0])

    def test_static_mode_toggles(self):
        assert paddle.in_dynamic_mode()
        paddle.enable_static()
        try:
            assert not paddle.in_dynamic_mode()
        finally:
            paddle.disable_static()
        assert paddle.in_dynamic_mode()
