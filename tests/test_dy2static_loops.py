"""dy2static round-4 breadth (VERDICT r3 item 7): for-range loops,
break/continue lowering, and/or/not over tensor predicates — all under
jit with traced operands, with eager behaviour unchanged.

Reference: python/paddle/jit/sot/ (bytecode conversion covers these
natively; here the AST rewrite gains the same subset)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import ConversionError, convert_control_flow


def _run(fn, *args):
    """Converted fn under jit on jnp args; returns numpy."""
    conv = convert_control_flow(fn)
    return np.asarray(jax.jit(conv)(*args))


class TestForRange:
    def test_tensor_trip_count(self):
        def f(x, n):
            s = x * 0.0
            for i in range(n):
                s = s + x + i
            return s

        x = jnp.asarray([1.0, 2.0])
        n = jnp.asarray(4)
        got = _run(f, x, n)
        np.testing.assert_allclose(got, np.asarray(f(np.ones(2) * 0 + np.asarray([1.0, 2.0]), 4)))

    def test_start_stop_step(self):
        def f(x, a, b):
            s = x * 0.0
            for i in range(a, b, 2):
                s = s + i
            return s

        x = jnp.asarray([0.0])
        got = _run(f, x, jnp.asarray(1), jnp.asarray(8))
        np.testing.assert_allclose(got, [1 + 3 + 5 + 7])

    def test_zero_trips(self):
        def f(x, n):
            s = x + 1.0
            for i in range(n):
                s = s * 10.0
            return s

        got = _run(f, jnp.asarray([2.0]), jnp.asarray(0))
        np.testing.assert_allclose(got, [3.0])

    def test_concrete_range_unchanged(self):
        def f(x):
            s = x
            for i in range(3):
                s = s + i
            return s

        got = _run(f, jnp.asarray([1.0]))
        np.testing.assert_allclose(got, [4.0])

    def test_python_iterable_still_works(self):
        def f(x):
            s = x
            for w in [1.0, 2.0, 3.0]:
                s = s + w
            return s

        got = _run(f, jnp.asarray([0.0]))
        np.testing.assert_allclose(got, [6.0])

    def test_traced_tensor_iterable_unrolls(self):
        # round-5: tensor iteration converts (static leading-axis unroll,
        # the jax/SOT semantics) instead of raising.
        def f(x):
            s = x[0] * 0.0
            for v in x:
                s = s + v * 2.0
            return s

        got = _run(f, jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]))
        np.testing.assert_allclose(got, [18.0, 24.0])

    def test_huge_tensor_iterable_diagnosed(self):
        # past the unroll limit the actionable error (and the to_static
        # eager fallback) is preserved rather than emitting a giant jaxpr
        def f(x):
            s = x[0] * 0.0
            for v in x:
                s = s + v
            return s

        conv = convert_control_flow(f)
        with pytest.raises(ConversionError, match="unroll"):
            jax.jit(conv)(jnp.zeros((257, 2)))

    def test_wrapped_huge_tensor_iterable_guarded(self):
        # review r5: enumerate/zip bypass check_iterable (the rewriter
        # guards the whole iterator expression), so Tensor.__iter__ itself
        # must enforce the unroll limit under trace.
        from paddle_tpu.core.tensor import TracedIterationError

        def f(x):
            s = x[0] * 0.0
            for i, v in enumerate(paddle.to_tensor(x)):
                s = s + v
            return s._value

        with pytest.raises(TracedIterationError, match="unroll"):
            jax.jit(f)(jnp.zeros((300, 2)))

    def test_wrapped_huge_tensor_for_falls_back_under_to_static(self):
        from paddle_tpu.jit import to_static

        def fwd(x):
            s = x[0] * 0.0
            for i, v in enumerate(x):
                s = s + v
            return s

        sf = to_static(fwd)
        x = paddle.to_tensor(np.ones((300, 2), np.float32))
        with pytest.warns(UserWarning, match="falling back to the EAGER"):
            out = sf(x)
        np.testing.assert_allclose(np.asarray(out._value), [300.0, 300.0])

    def test_traced_scalar_iterable_diagnosed(self):
        def f(x):
            s = 0.0
            for v in x:
                s = s + v
            return s

        conv = convert_control_flow(f)
        with pytest.raises(ConversionError, match="0-d"):
            jax.jit(conv)(jnp.asarray(3.0))

    def test_enumerate_over_traced_tensor(self):
        def f(x):
            s = x[0] * 0.0
            for i, row in enumerate(paddle.to_tensor(x)):
                s = s + row * float(i)
            return s._value

        got = np.asarray(jax.jit(convert_control_flow(f))(
            jnp.asarray([[1.0], [2.0], [3.0]])))
        np.testing.assert_allclose(got, [2.0 + 6.0])

    def test_tensor_for_with_concrete_break(self):
        def f(x):
            s = x[0] * 0.0
            for i, row in enumerate(x):
                if i >= 2:          # concrete predicate: plain Python break
                    break
                s = s + row
            return s

        got = _run(f, jnp.asarray([[1.0], [2.0], [4.0], [8.0]]))
        np.testing.assert_allclose(got, [3.0])


class TestBreakContinue:
    def test_break_in_while(self):
        def f(x, limit):
            i = jnp.asarray(0)
            s = x * 0.0
            while i < 100:
                if (i >= limit):
                    break
                s = s + x
                i = i + 1
            return s

        x = jnp.asarray([1.0])
        got = _run(f, x, jnp.asarray(5))
        np.testing.assert_allclose(got, [5.0])

    def test_continue_in_for(self):
        def f(x, n):
            s = x * 0.0
            for i in range(n):
                if (i % 2 == 0):
                    continue
                s = s + i
            return s

        got = _run(f, jnp.asarray([0.0]), jnp.asarray(6))
        np.testing.assert_allclose(got, [1 + 3 + 5])

    def test_break_in_for(self):
        def f(x, n):
            s = x * 0.0
            for i in range(10):
                if (i == n):
                    break
                s = s + 1.0
            return s

        got = _run(f, jnp.asarray([0.0]), jnp.asarray(4))
        np.testing.assert_allclose(got, [4.0])

    def test_statements_after_break_guard(self):
        """Statements following the breaking `if` are skipped once the
        flag is set."""
        def f(x, n):
            s = x * 0.0
            for i in range(6):
                if (i >= n):
                    break
                s = s + 1.0
                s = s + 0.5
            return s

        got = _run(f, jnp.asarray([0.0]), jnp.asarray(3))
        np.testing.assert_allclose(got, [4.5])

    def test_eager_behaviour_unchanged(self):
        def f(n):
            s = 0
            for i in range(10):
                if i == n:
                    break
                if i % 2 == 0:
                    continue
                s += i
            return s

        conv = convert_control_flow(f)
        assert conv(7) == f(7) == 1 + 3 + 5
        assert conv(0) == f(0) == 0

    def test_nested_loop_and_branch(self):
        """The VERDICT's nested loop+branch case: inner break only exits
        the inner loop."""
        def f(x, m):
            total = x * 0.0
            for i in range(3):
                acc = x * 0.0
                for j in range(5):
                    if (j >= m):
                        break
                    acc = acc + 1.0
                total = total + acc + i
            return total

        got = _run(f, jnp.asarray([0.0]), jnp.asarray(2))
        # inner contributes 2 each round; outer adds 0+1+2
        np.testing.assert_allclose(got, [3 * 2 + 3])


class TestBoolOps:
    def test_and_or_tensor_predicates(self):
        def f(x, y):
            if (x > 0) and (y > 0):
                r = x + y
            else:
                r = x - y
            return r

        got = _run(f, jnp.asarray(2.0), jnp.asarray(3.0))
        np.testing.assert_allclose(got, 5.0)
        got = _run(f, jnp.asarray(2.0), jnp.asarray(-3.0))
        np.testing.assert_allclose(got, 5.0)

    def test_or_and_not(self):
        def f(x, y):
            if (x > 0) or not (y > 0):
                r = x * 10.0
            else:
                r = y
            return r

        np.testing.assert_allclose(_run(f, jnp.asarray(-1.0),
                                        jnp.asarray(-2.0)), -10.0)
        np.testing.assert_allclose(_run(f, jnp.asarray(-1.0),
                                        jnp.asarray(2.0)), 2.0)

    def test_python_shortcircuit_preserved(self):
        """Concrete operands keep exact Python semantics: `a or b`
        returns the operand, not a bool, and short-circuits."""
        calls = []

        def f(x):
            def side():
                calls.append(1)
                return 7
            v = 5 or side()
            w = 0 or side()
            if (x > 0):
                r = x + v + w
            else:
                r = x
            return r

        got = _run(f, jnp.asarray(1.0))
        np.testing.assert_allclose(got, 1 + 5 + 7)
        # `5 or side()` must NOT have evaluated side(); `0 or side()` must
        # have evaluated it exactly once per trace
        assert len(calls) == 1

    def test_while_with_compound_predicate(self):
        def f(x, cap):
            i = jnp.asarray(0)
            while (i < 50) and (x[0] + i < cap):
                i = i + 1
            return i

        got = _run(f, jnp.asarray([3.0]), jnp.asarray(10.0))
        assert got == 7


class TestRealModelPath:
    def test_greedy_decode_loop_with_break(self):
        """A real serving-shaped path: an imperative greedy decode loop
        over the tiny llama stack, with EOS break, converted end-to-end
        and jitted (data-dependent EOS -> lax control flow)."""
        from paddle_tpu.models import llama as L
        cfg = L.llama_tiny(num_hidden_layers=2)
        params = L.init_stacked_params(cfg, seed=0)
        rng = np.random.RandomState(0)
        prompt = rng.randint(1, cfg.vocab_size, (1, 5)).astype(np.int32)

        # reference greedy tokens
        ref = []
        seq = prompt.copy()
        for _ in range(6):
            lg = L.forward_stacked(params, jnp.asarray(seq), cfg)
            nxt = int(np.asarray(jnp.argmax(lg[0, -1].astype(jnp.float32))))
            ref.append(nxt)
            seq = np.concatenate([seq, [[nxt]]], 1).astype(np.int32)
        eos = ref[3]

        P = prompt.shape[1]

        def decode(ids, eos_tok):
            # static (1, P+6) buffer; causal attention makes logits at the
            # last REAL position exact regardless of right padding — the
            # imperative EOS-break loop a user writes before learning scan
            buf = jnp.zeros((1, P + 6), jnp.int32)
            buf = jax.lax.dynamic_update_slice(buf, ids, (0, 0))
            out = jnp.zeros((6,), jnp.int32)
            count = jnp.asarray(0)
            for i in range(6):
                lg = L.forward_stacked(params, buf, cfg)
                nxt = jnp.take(lg[0], P - 1 + i, axis=0)
                nxt = jnp.argmax(nxt.astype(jnp.float32)).astype(jnp.int32)
                out = out.at[i].set(nxt)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[None, None], (0, P + i))
                count = count + 1
                if (nxt == eos_tok):
                    break
            return out, count

        conv = convert_control_flow(decode)
        out, count = jax.jit(conv)(jnp.asarray(prompt), jnp.asarray(eos))
        assert int(count) == 4
        got = [int(t) for t in np.asarray(out)[:4]]
        assert got == ref[:4]


class TestReviewRepros:
    """Round-4 review findings: cases the first test matrix missed."""

    def test_statement_level_break(self):
        """A bare (unconditional-position) break must terminate the traced
        loop exactly like the eager one."""
        def h(x):
            s = x * 0.0
            while (s.sum() < 10.0):
                s = s + x
                break
            return s

        conv = convert_control_flow(h)
        x = jnp.asarray(np.ones(4, np.float32))
        eager = np.asarray(conv(x))
        traced = np.asarray(jax.jit(conv)(x))
        np.testing.assert_allclose(eager, np.ones(4))
        np.testing.assert_allclose(traced, eager)

    def test_value_position_or_keeps_python_semantics(self):
        """`x or default` in VALUE position is not rewritten: concrete
        operands keep exact Python results; traced operands fail loudly
        (TracerBoolConversionError) instead of silently becoming a bool
        tensor."""
        def f(x):
            scale = x.sum() or 1.0
            if (scale > 0):
                r = x * scale
            else:
                r = x
            return r

        conv = convert_control_flow(f)
        x = jnp.asarray(np.full(4, 2.0, np.float32))
        np.testing.assert_allclose(np.asarray(conv(x)), 16.0)  # scale == 8
        with pytest.raises(jax.errors.TracerBoolConversionError):
            jax.jit(conv)(x)

    def test_break_inside_with(self):
        """break under a context manager lowers like any other break."""
        import contextlib

        def f(x, n):
            s = x * 0.0
            i = jnp.asarray(0)
            while (i < 10):
                with contextlib.nullcontext():
                    if (i >= n):
                        break
                    s = s + x
                i = i + 1
            return s

        got = _run(f, jnp.asarray([1.0]), jnp.asarray(3))
        np.testing.assert_allclose(got, [3.0])

    def test_nested_concrete_loop_with_local_counter(self):
        """A nested concrete while whose counter is a Python int local must
        not trip the undefined-carry probe (it IS assigned before read)."""
        def f(x):
            s = x * 0.0
            while (s.sum() < 10.0):
                k = 0
                while k < 3:
                    k = k + 1
                s = s + x + (k - 3)
            return s

        got = _run(f, jnp.asarray(np.ones(2, np.float32)))
        np.testing.assert_allclose(got, [5.0, 5.0])

    def test_inner_loop_break_not_attributed_to_outer(self):
        """A break inside an inner CONCRETE for belongs to that loop; the
        outer while must not grow escape flags or reject try-wrapping."""
        def f(x):
            s = x * 0.0
            while (s.sum() < 6.0):
                try:
                    for j in range(5):
                        if j == 2:
                            break
                except ValueError:
                    pass
                s = s + x + j - 2
            return s

        got = _run(f, jnp.asarray(np.ones(2, np.float32)))
        np.testing.assert_allclose(got, [3.0, 3.0])
