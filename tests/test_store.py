"""TCPStore rendezvous tests (SURVEY.md §2.3 TCPStore row).

Covers both the native C++ daemon/client (ctypes) and the pure-Python
fallback, plus cross-backend interop.
"""

import threading

import pytest

from paddle_tpu.distributed.store import MasterDaemon, TCPStore, native_lib


def _roundtrip(prefer_native):
    store = TCPStore(is_master=True, world_size=1, timeout=10.0,
                     prefer_native=prefer_native)
    try:
        store.set("alpha", b"hello")
        assert store.get("alpha") == b"hello"
        store.set("alpha", "world")
        assert store.get("alpha") == b"world"
        assert store.add("cnt", 3) == 3
        assert store.add("cnt", -1) == 2
        store.wait("cnt", timeout=1.0)
        with pytest.raises(TimeoutError):
            store.get("missing", timeout=0.3)
        store.delete_key("alpha")
        with pytest.raises(TimeoutError):
            store.get("alpha", timeout=0.3)
    finally:
        store.close()
    return store


def test_python_backend_roundtrip():
    store = _roundtrip(prefer_native=False)
    assert store.backend in ("python", "native")  # closed; attr still valid


def test_native_backend_roundtrip():
    if native_lib() is None:
        pytest.skip("no C++ toolchain")
    store = TCPStore(is_master=True, world_size=1, timeout=10.0)
    try:
        assert store.backend == "native"
        assert store.daemon.backend == "native"
        store.set("k", b"v")
        assert store.get("k") == b"v"
    finally:
        store.close()
    _roundtrip(prefer_native=True)


def test_interop_python_client_native_daemon():
    if native_lib() is None:
        pytest.skip("no C++ toolchain")
    daemon = MasterDaemon(prefer_native=True)
    assert daemon.backend == "native"
    try:
        c = TCPStore(host="127.0.0.1", port=daemon.port, timeout=10.0,
                     prefer_native=False)
        c.set("x", b"42")
        assert c.add("n", 5) == 5
        c2 = TCPStore(host="127.0.0.1", port=daemon.port, timeout=10.0,
                      prefer_native=True)
        assert c2.get("x") == b"42"
        assert c2.add("n", 1) == 6
        c.close()
        c2.close()
    finally:
        daemon.stop()


def test_blocking_get_wakes_on_set():
    daemon = MasterDaemon(prefer_native=False)
    try:
        got = {}

        def getter():
            c = TCPStore(host="127.0.0.1", port=daemon.port, timeout=10.0,
                         prefer_native=False)
            got["v"] = c.get("late", timeout=5.0)
            c.close()

        t = threading.Thread(target=getter)
        t.start()
        setter = TCPStore(host="127.0.0.1", port=daemon.port, timeout=10.0,
                          prefer_native=False)
        import time
        time.sleep(0.2)
        setter.set("late", b"arrived")
        t.join(timeout=5.0)
        assert got.get("v") == b"arrived"
        setter.close()
    finally:
        daemon.stop()


@pytest.mark.parametrize("prefer_native", [False, True])
def test_barrier_multi_client(prefer_native):
    if prefer_native and native_lib() is None:
        pytest.skip("no C++ toolchain")
    n = 4
    daemon = MasterDaemon(prefer_native=prefer_native)
    try:
        done = []
        lock = threading.Lock()

        def worker(rank):
            c = TCPStore(host="127.0.0.1", port=daemon.port, world_size=n,
                         timeout=10.0, prefer_native=prefer_native)
            c.barrier("b0")
            c.barrier("b0")  # second round must not collide with first
            with lock:
                done.append(rank)
            c.close()

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15.0)
        assert sorted(done) == list(range(n))
    finally:
        daemon.stop()


@pytest.mark.parametrize("prefer_native", [False, True])
def test_stalled_client_does_not_wedge_daemon(prefer_native):
    """A client that sends only a partial request (header, no key) must not
    block other clients' operations — review regression for the blocking
    recv in the single-threaded daemon."""
    import socket
    import struct
    import time
    if prefer_native and native_lib() is None:
        pytest.skip("no C++ toolchain")
    daemon = MasterDaemon(prefer_native=prefer_native)
    try:
        stalled = socket.create_connection(("127.0.0.1", daemon.port))
        # header claims a 100-byte key but we never send it
        stalled.sendall(struct.pack("<BI", 1, 100))
        time.sleep(0.2)

        c = TCPStore(host="127.0.0.1", port=daemon.port, world_size=1,
                     timeout=5.0, prefer_native=prefer_native)
        c.set("k", b"v")                    # would hang if daemon is wedged
        assert c.get("k", timeout=5.0) == b"v"
        c.close()
        stalled.close()
    finally:
        daemon.stop()


def test_barrier_reclaims_previous_round_keys():
    """Barrier rounds must not leak keys into the master map."""
    daemon = MasterDaemon(prefer_native=False)
    try:
        c = TCPStore(host="127.0.0.1", port=daemon.port, world_size=1,
                     timeout=5.0, prefer_native=False)
        for _ in range(5):
            c.barrier("leak")
        kv = daemon._py._kv
        barrier_keys = [k for k in kv if k.startswith(b"/barrier/leak/r")]
        # only the latest round's two keys may remain
        assert len(barrier_keys) <= 2, barrier_keys
        c.close()
    finally:
        daemon.stop()
