"""GPT-MoE (workload #4): expert-parallel FFN blocks under a mesh axis.

Parity: the expert-parallel model must track the dense-dispatch model, and
training through the fleet-compiled hybrid step must reduce the loss."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.distributed import collective as C
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import topology as topo
from paddle_tpu.parallel import mesh as pmesh
from paddle_tpu.models.gpt_moe import GPTMoEForCausalLM, gpt_moe_tiny

pytestmark = pytest.mark.slow  # core tier: -m 'not slow'


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    pmesh.set_global_mesh(None)
    topo.set_hybrid_communicate_group(None)


def _batch(cfg, b=8, s=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = np.roll(ids, -1, axis=-1).astype(np.int64)
    return paddle.to_tensor(ids), paddle.to_tensor(labels)


def test_gpt_moe_expert_parallel_matches_dense_forward():
    cfg = gpt_moe_tiny(moe_gate="naive", capacity_factor=(100.0, 100.0))
    mesh = pmesh.build_mesh({"dp": 8})
    pmesh.set_global_mesh(mesh)
    group = C.Group("dp", mesh)

    paddle.seed(7)
    dense = GPTMoEForCausalLM(cfg)
    paddle.seed(7)
    ep = GPTMoEForCausalLM(cfg, moe_group=group)
    assert any(getattr(b.mlp, "_ep_parts", None) is not None
               for b in ep.blocks)
    ids, labels = _batch(cfg)
    ld = float(dense.compute_loss(ids, labels))
    lp = float(ep.compute_loss(ids, labels))
    np.testing.assert_allclose(lp, ld, rtol=1e-4)


def test_gpt_moe_trains_through_fleet_step():
    cfg = gpt_moe_tiny(moe_gate="gshard")
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    group = C.Group("dp", pmesh.get_global_mesh())

    paddle.seed(1)
    net = GPTMoEForCausalLM(cfg, moe_group=group)
    opt = optimizer.AdamW(learning_rate=3e-3, parameters=net.parameters())
    dm = fleet.distributed_model(net)
    dopt = fleet.distributed_optimizer(opt)
    step = dm.compile_train_step(lambda m, i, l: m.compute_loss(i, l), dopt)
    ids, labels = _batch(cfg, b=16)
    losses = [float(step(ids, labels)) for _ in range(6)]
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0], losses


def test_gpt_moe_amp_recompute_composition():
    """amp O1 + recompute + expert-parallel MoE composed through the fleet
    strategy (caught a real escaped-tracer bug: MoE l_aux written inside the
    jax.checkpoint region must be threaded out as a checkpoint output)."""
    cfg = gpt_moe_tiny()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8}
    strategy.amp = True
    strategy.amp_configs = {"level": "O1", "dtype": "bfloat16"}
    strategy.recompute = True
    strategy.recompute_configs = {"checkpoints": ["blocks.0"]}
    fleet.init(is_collective=True, strategy=strategy)
    group = C.Group("dp", pmesh.get_global_mesh())
    paddle.seed(0)
    net = GPTMoEForCausalLM(cfg, moe_group=group)
    opt = optimizer.AdamW(learning_rate=3e-3, parameters=net.parameters())
    dm = fleet.distributed_model(net)
    dopt = fleet.distributed_optimizer(opt)
    step = dm.compile_train_step(lambda m, i, l: m.compute_loss(i, l), dopt)
    ids, labels = _batch(cfg, b=16)
    losses = [float(step(ids, labels)) for _ in range(5)]
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0], losses
