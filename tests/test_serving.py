"""paddle_tpu.serving: SLO-aware scheduler, streaming, backpressure,
robustness and metrics over the continuous-batching engine (ISSUE 1).

Seeded arrival traces on the tiny stacked llama; the engine seed plus a
deterministic trace makes every assertion reproducible."""

import re
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from paddle_tpu.models import llama as L
from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                           GenerationConfig)
from paddle_tpu.profiler.record import host_recorder
from paddle_tpu.serving import (RequestState, SchedulerConfig, ServingError,
                                ServingMetrics, ServingScheduler)

REPO = Path(__file__).resolve().parent.parent


class FakeClock:
    """Deterministic scheduler clock; sleep() advances it."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt

    def advance(self, dt):
        self.t += dt


def _setup(max_new=5, num_slots=2, chunk=2, seed=3, do_sample=False,
           max_queue_depth=64, clock=None, **sched_kw):
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=seed)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=max_new, do_sample=do_sample,
                              seed=seed),
        num_slots=num_slots, page_size=4, max_seq_len=32, chunk=chunk)
    clock = clock or FakeClock()
    sched = ServingScheduler(
        eng, SchedulerConfig(max_queue_depth=max_queue_depth, **sched_kw),
        clock=clock, sleep=clock.sleep)
    return cfg, params, eng, sched, clock


def _prompts(cfg, n, rng_seed=0, lens=(3, 8)):
    rng = np.random.RandomState(rng_seed)
    return [rng.randint(1, cfg.vocab_size,
                        (int(rng.randint(lens[0], lens[1] + 1)),)
                        ).astype(np.int32) for _ in range(n)]


def _greedy_ref(params, cfg, prompt, n_new):
    import jax.numpy as jnp
    seq = np.asarray(prompt, np.int32)[None, :]
    out = []
    for _ in range(n_new):
        logits = L.forward_stacked(params, jnp.asarray(seq), cfg)
        nxt = int(np.asarray(jnp.argmax(logits[0, -1].astype(jnp.float32))))
        out.append(nxt)
        seq = np.concatenate([seq, [[nxt]]], axis=1).astype(np.int32)
    return out


# ---------------------------------------------------------------------------
# scheduling policy
# ---------------------------------------------------------------------------

def test_priority_ordering_fifo_within_class():
    """With one slot, admission strictly follows (priority, arrival):
    engine rids are handed out in admission order."""
    cfg, params, eng, sched, _ = _setup(num_slots=1)
    ps = _prompts(cfg, 5, rng_seed=1)
    # arrival order: priorities 2, 0, 1, 0, 2
    handles = [sched.submit(p, priority=pr)
               for p, pr in zip(ps, (2, 0, 1, 0, 2))]
    sched.run(params, max_steps=200)
    assert all(h.state == RequestState.DONE for h in handles)
    admission = sorted(range(5), key=lambda i: handles[i].engine_rid)
    # priority 0 first (FIFO: rid1 before rid3), then 1, then 2 (rid0, rid4)
    assert admission == [1, 3, 2, 0, 4]


def test_outputs_match_engine_serve_oracle():
    """The scheduler is a lifecycle layer: per-request tokens equal the
    greedy full-reforward oracle, same as engine.serve."""
    cfg, params, eng, sched, _ = _setup(max_new=4, num_slots=2)
    ps = _prompts(cfg, 4, rng_seed=2)
    hs = [sched.submit(p) for p in ps]
    sched.run(params, max_steps=200)
    for p, h in zip(ps, hs):
        assert h.stream.result() == _greedy_ref(params, cfg, p, 4)


def test_per_request_max_new_tokens():
    cfg, params, eng, sched, _ = _setup(max_new=5)
    ps = _prompts(cfg, 2, rng_seed=3)
    h_short = sched.submit(ps[0], max_new_tokens=2)
    h_long = sched.submit(ps[1])
    sched.run(params, max_steps=200)
    assert len(h_short.stream.tokens) == 2
    assert len(h_long.stream.tokens) == 5
    assert h_short.stream.result() == _greedy_ref(params, cfg, ps[0], 2)


def test_queue_overflow_sheds_lowest_priority_latest_deadline():
    cfg, params, eng, sched, clock = _setup(max_queue_depth=3)
    ps = _prompts(cfg, 5, rng_seed=4)
    h0 = sched.submit(ps[0], priority=0, deadline_ms=100)
    h1 = sched.submit(ps[1], priority=1, deadline_ms=50)
    h2 = sched.submit(ps[2], priority=1, deadline_ms=500)   # latest deadline
    h3 = sched.submit(ps[3], priority=1, deadline_ms=200)   # overflow: shed h2
    assert h2.state == RequestState.SHED
    assert h2.stream.finish_reason == "shed:queue_full"
    with pytest.raises(ServingError) as ei:
        h2.stream.result()
    assert ei.value.code == "shed_queue_full"
    # no-deadline request sheds before deadlined peers of the same class
    h4 = sched.submit(ps[4], priority=1)
    assert h4.state == RequestState.SHED
    assert sched.metrics.shed == {"queue_full": 2}
    sched.run(params, max_steps=200)
    assert all(h.state == RequestState.DONE for h in (h0, h1, h3))


def test_deadline_expiry_sheds_queued_request():
    """A request still queued past its deadline is shed, not decoded."""
    cfg, params, eng, sched, clock = _setup(num_slots=1)
    ps = _prompts(cfg, 2, rng_seed=5)
    h_ok = sched.submit(ps[0], priority=0)
    h_late = sched.submit(ps[1], priority=1, deadline_ms=50)
    clock.advance(0.2)          # deadline (50 ms) lapses while queued
    sched.run(params, max_steps=200)
    assert h_ok.state == RequestState.DONE
    assert h_late.state == RequestState.SHED
    assert h_late.stream.finish_reason == "shed:deadline"
    assert h_late.stream.tokens == []
    assert sched.metrics.shed == {"deadline": 1}


def test_mid_decode_cancellation_frees_slot_and_pages():
    cfg, params, eng, sched, _ = _setup(max_new=8, num_slots=2, chunk=2)
    free0 = eng.mgr.num_free_pages
    ps = _prompts(cfg, 2, rng_seed=6)
    h0 = sched.submit(ps[0])
    h1 = sched.submit(ps[1])
    # step until h0 is mid-decode (prefill rides the unified ragged step,
    # so the first tokens land a round after admission, not with it)
    for _ in range(10):
        sched.step(params)
        if h0.stream.tokens:
            break
    assert h0.state == RequestState.RUNNING and len(h0.stream.tokens) >= 1
    assert sched.cancel(h0.rid)
    # slot + pages reclaimed immediately, stream closed as cancelled
    assert eng._slot_rid.count(None) == 1
    assert h0.state == RequestState.CANCELLED
    assert h0.stream.finish_reason == "cancelled"
    assert not sched.cancel(h0.rid)         # idempotent: already finished
    sched.run(params, max_steps=200)        # survivor completes normally
    assert h1.stream.result() == _greedy_ref(params, cfg, ps[1], 8)
    assert eng.mgr.num_free_pages == free0
    assert sched.metrics.counters["requests_cancelled_total"] == 1


def test_on_token_callback_can_cancel_own_request():
    """A stop-sequence-style on_token callback may cancel its own request
    mid-chunk; the engine's unpack loop must survive the reentrant retire
    and keep delivering that chunk's tokens to the other slots."""
    cfg, params, eng, sched, _ = _setup(max_new=6, num_slots=2, chunk=2)
    free0 = eng.mgr.num_free_pages
    ps = _prompts(cfg, 2, rng_seed=16)
    box = {}
    h0 = sched.submit(ps[0], on_token=lambda t: sched.cancel(box["rid"]))
    box["rid"] = h0.rid
    h1 = sched.submit(ps[1])
    sched.run(params, max_steps=200)
    assert h0.state == RequestState.CANCELLED
    assert len(h0.stream.tokens) == 1       # stopped after the first token
    assert h1.state == RequestState.DONE
    assert h1.stream.result() == _greedy_ref(params, cfg, ps[1], 6)
    assert eng.mgr.num_free_pages == free0


def test_finished_requests_evicted_from_registry():
    """The scheduler registry must not grow without bound in a
    long-running server: resolved requests are evicted (the caller keeps
    the handle; cancel() on a finished rid stays a no-op)."""
    cfg, params, eng, sched, _ = _setup()
    hs = [sched.submit(p) for p in _prompts(cfg, 3, rng_seed=17)]
    sched.run(params, max_steps=200)
    assert all(h.state == RequestState.DONE for h in hs)
    assert sched._requests == {}
    assert not sched.cancel(hs[0].rid)


def test_cancel_queued_request_never_reaches_engine():
    cfg, params, eng, sched, _ = _setup(num_slots=1)
    ps = _prompts(cfg, 2, rng_seed=7)
    h0 = sched.submit(ps[0])
    h1 = sched.submit(ps[1])
    assert sched.cancel(h1.rid)
    sched.run(params, max_steps=200)
    assert h0.state == RequestState.DONE
    assert h1.state == RequestState.CANCELLED and h1.engine_rid is None


# ---------------------------------------------------------------------------
# streaming
# ---------------------------------------------------------------------------

def test_tokens_stream_at_chunk_cadence():
    """Tokens surface after every step (chunk granularity), not at the
    end; drain() and on_token agree with the final result."""
    cfg, params, eng, sched, _ = _setup(max_new=6, num_slots=1, chunk=2)
    seen_cb = []
    h = sched.submit(_prompts(cfg, 1, rng_seed=8)[0],
                     on_token=seen_cb.append)
    drained = []
    growth = []
    while sched.pending:
        sched.step(params)
        new = h.stream.drain()
        drained.extend(new)
        growth.append(len(new))
    assert drained == seen_cb == h.stream.result()
    assert len(drained) == 6
    # incremental: at least one step delivered a strict prefix
    assert any(0 < g < 6 for g in growth)


def test_blocking_iterator_from_consumer_thread():
    cfg, params, eng, sched, _ = _setup(max_new=4, num_slots=1, chunk=2)
    h = sched.submit(_prompts(cfg, 1, rng_seed=9)[0])
    got = []
    t = threading.Thread(target=lambda: got.extend(h.stream))
    t.start()
    sched.run(params, max_steps=200)
    t.join(timeout=10)
    assert not t.is_alive()
    assert got == h.stream.result() and len(got) == 4


def test_infeasible_request_rejected_at_submit():
    """A request that could never be admitted (prompt+budget beyond
    max_seq_len, or more KV pages than the whole pool) raises ValueError
    at submit instead of leaking into the queue or degrading the loop."""
    cfg, params, eng, sched, _ = _setup(max_new=5)      # max_seq_len=32
    with pytest.raises(ValueError, match="max_seq_len"):
        sched.submit(np.ones(40, np.int32))
    eng2 = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=5), num_slots=2, page_size=4,
        max_seq_len=32, num_pages=2, chunk=2)           # 1 usable page
    sched2 = ServingScheduler(eng2)
    with pytest.raises(ValueError, match="KV pages"):
        sched2.submit(np.ones(8, np.int32))             # needs 4 pages
    assert sched.pending == 0 and sched2.pending == 0   # nothing leaked


def test_page_pressure_no_priority_inversion():
    """Free slot but scarce pages: waiting requests stay in the SCHEDULER
    queue (the engine FIFO never buffers), so a later higher-priority
    submission is admitted first once pages free up."""
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=3)
    # pool = 2 usable pages = exactly one request (4 prompt + 4 new)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=4), num_slots=2, page_size=4,
        max_seq_len=32, num_pages=3, chunk=2)
    clock = FakeClock()
    sched = ServingScheduler(eng, SchedulerConfig(), clock=clock,
                             sleep=clock.sleep)
    rng = np.random.RandomState(15)

    def prompt():
        return rng.randint(1, cfg.vocab_size, (4,)).astype(np.int32)

    h_a = sched.submit(prompt(), priority=1)
    sched.step(params)                      # A admitted, pool exhausted
    h_b = sched.submit(prompt(), priority=1)
    h_c = sched.submit(prompt(), priority=0)  # later arrival, more urgent
    while sched.pending:
        sched.step(params)
        assert not eng._queue               # engine FIFO stays empty
    assert all(h.state == RequestState.DONE for h in (h_a, h_b, h_c))
    assert h_c.engine_rid < h_b.engine_rid  # no inversion behind the FIFO


# ---------------------------------------------------------------------------
# robustness
# ---------------------------------------------------------------------------

def test_injected_step_failure_retried_with_backoff():
    cfg, params, eng, sched, clock = _setup(
        max_new=4, retry_backoff_s=0.05, retry_backoff_multiplier=2.0,
        max_step_retries=3)
    real_step = eng.step
    fails = {"n": 2}
    calls = []

    def flaky_step(p):
        calls.append(clock())
        if fails["n"] > 0:
            fails["n"] -= 1
            raise RuntimeError("injected device fault")
        return real_step(p)

    eng.step = flaky_step
    h = sched.submit(_prompts(cfg, 1, rng_seed=10)[0])
    sched.run(params, max_steps=200)
    assert h.state == RequestState.DONE
    assert h.stream.result() == _greedy_ref(
        params, cfg, h.prompt, 4)
    m = sched.metrics.counters
    assert m["step_retries_total"] == 2
    assert m["step_failures_total"] == 2
    assert not sched.degraded
    # exponential backoff between the failed attempts: 0.05 then 0.1
    gaps = np.diff([c for c in calls[:3]])
    assert gaps[0] == pytest.approx(0.05) and gaps[1] == pytest.approx(0.1)


def test_repeated_failure_degrades_gracefully():
    """After the retry budget, in-flight AND queued requests drain with a
    structured error; the loop does not crash and resources are freed."""
    cfg, params, eng, sched, _ = _setup(
        num_slots=1, max_step_retries=2, retry_backoff_s=0.01)
    free0 = eng.mgr.num_free_pages

    def always_fail(p):
        raise RuntimeError("persistent device fault")

    eng.step = always_fail
    ps = _prompts(cfg, 3, rng_seed=11)
    hs = [sched.submit(p) for p in ps]
    sched.run(params, max_steps=200)        # returns instead of raising
    assert sched.degraded
    assert all(h.state == RequestState.FAILED for h in hs)
    for h in hs:
        with pytest.raises(ServingError) as ei:
            h.stream.result()
        assert ei.value.code == "engine_failure"
    assert sched.metrics.counters["step_retries_total"] == 2
    assert sched.metrics.counters["step_failures_total"] == 3
    assert eng.mgr.num_free_pages == free0   # pages reclaimed on degrade
    with pytest.raises(ServingError):        # refuses new work
        sched.submit(ps[0])


def test_step_timeout_counts_as_failure():
    cfg, params, eng, sched, _ = _setup(
        step_timeout_s=0.05, max_step_retries=1, retry_backoff_s=0.01)

    def hung_step(p):
        time.sleep(0.5)

    eng.step = hung_step
    h = sched.submit(_prompts(cfg, 1, rng_seed=12)[0])
    sched.run(params, max_steps=200)
    assert sched.degraded and h.state == RequestState.FAILED
    assert sched.metrics.counters["step_failures_total"] == 2


def test_timed_out_step_never_runs_concurrently():
    """A slow-but-completing step must not race a retry's second
    engine.step: the retry waits on the straggler, and its eventual
    completion counts as the step."""
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=3)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=4), num_slots=2, page_size=4,
        max_seq_len=32, chunk=2)
    eng.serve(params, [np.arange(1, 5, dtype=np.int32)])   # warm compiles
    sched = ServingScheduler(eng, SchedulerConfig(
        step_timeout_s=0.05, max_step_retries=5, retry_backoff_s=0.01))
    real_step = eng.step
    lock = threading.Lock()
    state = {"active": 0, "max_active": 0, "calls": 0}

    def slow_first_step(p):
        with lock:
            state["active"] += 1
            state["max_active"] = max(state["max_active"], state["active"])
            state["calls"] += 1
            first = state["calls"] == 1
        try:
            if first:
                time.sleep(0.2)             # slower than the timeout
            return real_step(p)
        finally:
            with lock:
                state["active"] -= 1

    eng.step = slow_first_step
    h = sched.submit(np.arange(1, 5, dtype=np.int32))
    sched.run(params, max_steps=200)
    assert state["max_active"] == 1         # never two concurrent steps
    assert h.state == RequestState.DONE and len(h.stream.result()) == 4
    assert not sched.degraded
    assert sched.metrics.counters["step_failures_total"] >= 1


def test_determinism_under_fixed_seed():
    """Same sampled-decoding trace twice -> identical outputs."""

    def run_once():
        cfg, params, eng, sched, _ = _setup(
            max_new=5, num_slots=2, do_sample=True, seed=7)
        hs = [sched.submit(p, priority=pr) for p, pr in
              zip(_prompts(cfg, 6, rng_seed=13), (1, 0, 2, 0, 1, 2))]
        sched.run(params, max_steps=300)
        return [h.stream.result() for h in hs]

    assert run_once() == run_once()


def test_deferred_submit_admits_only_after_backoff():
    """submit(defer_s=...) parks the request on the retry/backoff path:
    not admitted while the clock is short of ready time, admitted (at
    its original priority/FIFO position) once it passes."""
    cfg, params, eng, sched, clock = _setup(num_slots=2)
    p = _prompts(cfg, 1, rng_seed=20)[0]
    h = sched.submit(p, defer_s=1.0)
    assert sched.pending == 1
    sched.step(params)
    assert h.state == RequestState.QUEUED and h.engine_rid is None
    assert sched.statusz()["backoff"] == 1
    clock.advance(1.5)
    sched.run(params, max_steps=200)
    assert h.state == RequestState.DONE
    assert h.stream.result() == _greedy_ref(params, cfg, p, 5)


def test_run_waits_out_backoff_instead_of_spinning():
    """run() with only deferred requests pending sleeps the backoff
    instead of burning max_steps on no-op rounds (fake-clock sleeps
    advance the clock, so the deferral comes due and completes)."""
    cfg, params, eng, sched, clock = _setup()
    h = sched.submit(_prompts(cfg, 1, rng_seed=26)[0], defer_s=2.0)
    sched.run(params, max_steps=60)     # would exhaust if hot-spinning
    assert h.state == RequestState.DONE


def test_cancel_in_backoff_queue_is_idempotent():
    """Regression (ISSUE 6 satellite): a request cancelled while parked
    in the backoff queue must NOT be re-admitted by a later retry tick —
    the cancel permanently removes it, and a second cancel is a no-op."""
    cfg, params, eng, sched, clock = _setup(num_slots=2)
    p = _prompts(cfg, 1, rng_seed=21)[0]
    h = sched.submit(p, defer_s=0.5)
    assert sched.cancel(h.rid)
    assert h.state == RequestState.CANCELLED
    assert h.stream.finish_reason == "cancelled"
    assert not sched.cancel(h.rid)          # idempotent
    clock.advance(2.0)                      # retry tick comes due...
    for _ in range(3):
        sched.step(params)
    # ...and must not resurrect the cancelled request
    assert sched.pending == 0
    assert h.engine_rid is None and not eng._queue and not eng._live
    assert sched.metrics.counters["requests_cancelled_total"] == 1
    assert sched.metrics.counters.get("requests_completed_total", 0) == 0


def test_promoted_backoff_request_exempt_from_queue_cap_shed():
    """A failover-remediation request (submit(defer_s=...)) promoted
    into a full queue must never be the queue_full victim — fresh load
    sheds around it (review fix: the promoted request used to be the
    newest arrival and thus the FIRST victim)."""
    cfg, params, eng, sched, clock = _setup(num_slots=1, max_queue_depth=2)
    ps = _prompts(cfg, 5, rng_seed=24)
    h_run = sched.submit(ps[0])
    sched.step(params)                          # occupies the only slot
    h_a = sched.submit(ps[1])
    h_b = sched.submit(ps[2])                   # queue now AT the cap
    h_remed = sched.submit(ps[3], defer_s=0.1)  # remediation traffic
    clock.advance(0.5)
    sched.step(params)          # promotion pushes the queue over cap:
    # the remediation request is the newest arrival (highest seq) but a
    # FRESH request must be the queue_full victim, never it
    assert h_remed.state != RequestState.SHED
    assert h_b.state == RequestState.SHED
    assert h_b.stream.finish_reason == "shed:queue_full"
    sched.run(params, max_steps=300)
    assert h_remed.state == RequestState.DONE
    assert h_remed.stream.result() == _greedy_ref(params, cfg, ps[3], 5)
    assert all(h.state == RequestState.DONE for h in (h_run, h_a))


def test_deferred_request_deadline_expires_in_backoff():
    cfg, params, eng, sched, clock = _setup()
    h = sched.submit(_prompts(cfg, 1, rng_seed=22)[0], deadline_ms=100,
                     defer_s=10.0)
    clock.advance(0.5)                      # deadline lapses while parked
    sched.step(params)
    assert h.state == RequestState.SHED
    assert h.stream.finish_reason == "shed:deadline"


def test_lapsed_deferred_request_never_displaces_fresh_load():
    """A deferred request whose deadline AND defer both lapsed must shed
    as deadline without transiting the queue — its no_shed exemption
    must not push a viable fresh request over the cap on the way out."""
    cfg, params, eng, sched, clock = _setup(num_slots=1, max_queue_depth=2)
    ps = _prompts(cfg, 4, rng_seed=25)
    h_run = sched.submit(ps[0])
    sched.step(params)                      # occupies the slot
    h_a = sched.submit(ps[1])
    h_b = sched.submit(ps[2])               # queue at the cap
    h_dead = sched.submit(ps[3], deadline_ms=50, defer_s=0.1)
    clock.advance(0.5)                      # defer due AND deadline gone
    sched.step(params)
    assert h_dead.state == RequestState.SHED
    assert h_dead.stream.finish_reason == "shed:deadline"
    assert h_a.state != RequestState.SHED   # nobody wrongfully displaced
    assert h_b.state != RequestState.SHED
    sched.run(params, max_steps=300)
    assert all(h.state == RequestState.DONE for h in (h_run, h_a, h_b))


# ---------------------------------------------------------------------------
# stream robustness: producers that die without closing
# ---------------------------------------------------------------------------

def test_stream_producer_death_unblocks_consumer():
    """A blocking consumer with NO timeout gets a terminal
    producer_dead error when the bound producer dies, instead of
    blocking indefinitely."""
    from paddle_tpu.serving import TokenStream
    alive = [True]
    stream = TokenStream(0)
    stream.attach_producer(lambda: alive[0], poll_s=0.01)
    got = []

    def consume():
        got.append(stream.get())            # blocking, timeout-free

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()                     # genuinely blocked
    alive[0] = False                        # producer dies silently
    t.join(timeout=10)
    assert not t.is_alive()
    assert got == [None]
    assert stream.finished and stream.finish_reason == "failed"
    with pytest.raises(ServingError) as ei:
        stream.result()
    assert ei.value.code == "producer_dead"


def test_fatal_engine_death_closes_streams_terminally():
    """An engine dying with a non-Exception BaseException (fatal runtime
    death) skips the finish callback entirely; the scheduler must drain
    every stream with a terminal error before re-raising, so a blocked
    consumer thread unblocks without any timeout."""

    class FatalDeath(BaseException):
        pass

    cfg, params, eng, sched, _ = _setup(num_slots=2)

    def dying_step(p):
        raise FatalDeath("runtime died")

    eng.step = dying_step
    hs = [sched.submit(p) for p in _prompts(cfg, 2, rng_seed=23)]
    got = []
    t = threading.Thread(target=lambda: got.extend(hs[0].stream))
    t.start()
    with pytest.raises(FatalDeath):
        sched.step(params)
    t.join(timeout=10)
    assert not t.is_alive()                 # consumer unblocked
    assert sched.degraded
    for h in hs:
        assert h.state == RequestState.FAILED
        with pytest.raises(ServingError) as ei:
            h.stream.result()
        assert ei.value.code == "engine_failure"


# ---------------------------------------------------------------------------
# end-to-end acceptance + metrics
# ---------------------------------------------------------------------------

def test_e2e_serving_mixed_priorities_with_metrics():
    """ISSUE 1 acceptance: >=16 concurrent mixed-priority streaming
    requests; one cancelled mid-decode with pages reclaimed; one
    past-deadline request shed; one injected step failure retried with
    backoff; exported metrics text consistent with the trace."""
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=3)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=5), num_slots=4,
        page_size=4, max_seq_len=32, chunk=2)
    metrics = ServingMetrics()
    sched = ServingScheduler(
        eng, SchedulerConfig(max_queue_depth=32, max_step_retries=2,
                             retry_backoff_s=0.001), metrics=metrics)
    free0 = eng.mgr.num_free_pages

    real_step = eng.step
    fail_once = {"armed": True}

    def flaky_step(p):
        if fail_once["armed"]:
            fail_once["armed"] = False
            raise RuntimeError("injected transient fault")
        return real_step(p)

    eng.step = flaky_step

    host_recorder.enabled = True
    host_recorder.clear()
    try:
        rng = np.random.RandomState(14)
        handles = []
        for i in range(16):
            prompt = rng.randint(1, cfg.vocab_size,
                                 (int(rng.randint(3, 9)),)).astype(np.int32)
            handles.append(sched.submit(prompt, priority=i % 3))
        # a request whose deadline cannot be met from the back of the queue
        h_late = sched.submit(
            rng.randint(1, cfg.vocab_size, (4,)).astype(np.int32),
            priority=2, deadline_ms=1e-3)
        h_cancel = handles[5]

        # step until the first tokens land (unified step: long prompts
        # may spread their prefill over a couple of ragged rounds)
        for _ in range(20):
            sched.step(params)
            if any(len(h.stream.tokens) > 0 for h in handles):
                break
        assert any(len(h.stream.tokens) > 0 for h in handles)
        assert sched.cancel(h_cancel.rid)   # mid-decode or queued
        sched.run(params, max_steps=500)
    finally:
        host_recorder.enabled = False

    survivors = [h for h in handles if h is not h_cancel]
    assert all(h.state == RequestState.DONE for h in survivors)
    assert all(len(h.stream.result()) == 5 for h in survivors)
    assert h_late.state == RequestState.SHED
    assert h_cancel.state == RequestState.CANCELLED
    assert eng.mgr.num_free_pages == free0          # cancelled pages back
    assert all(r is None for r in eng._slot_rid)

    c = metrics.counters
    assert c["requests_submitted_total"] == 17
    assert c["requests_completed_total"] == 15
    assert c["requests_cancelled_total"] == 1
    assert metrics.shed == {"deadline": 1}
    assert c["step_retries_total"] >= 1
    assert c["tokens_generated_total"] == sum(
        len(h.stream.tokens) for h in handles)

    # TTFT/ITL histograms populated and consistent
    assert metrics.histograms["ttft_ms"].count >= 15
    assert metrics.histograms["itl_ms"].count > 0
    assert metrics.histograms["ttft_ms"].sum > 0
    assert metrics.histograms["queue_depth"].count > 0

    text = metrics.to_prometheus_text()
    m = re.search(r"paddle_serving_ttft_ms_count (\d+)", text)
    assert m and int(m.group(1)) >= 15
    assert re.search(r"paddle_serving_itl_ms_count [1-9]", text)
    assert 'paddle_serving_ttft_ms_quantile{quantile="0.99"}' in text
    assert 'paddle_serving_requests_shed_total{reason="deadline"} 1' in text
    assert re.search(r"paddle_serving_step_retries_total [1-9]", text)
    assert re.search(r"paddle_serving_queue_depth_count [1-9]", text)

    # trace events reached the profiler host recorder
    spans = host_recorder.drain()
    names = {s.name for s in spans}
    assert "paddle_serving.step" in names
    assert "paddle_serving.request" in names
    assert "paddle_serving.shed.deadline" in names
    assert "paddle_serving.step_retry" in names


# ---------------------------------------------------------------------------
# lint: the compat shim stays the single shard_map source
# ---------------------------------------------------------------------------

def test_no_direct_shard_map_imports():
    """Forbid new `from jax import shard_map` / `jax.shard_map(` uses;
    paddle_tpu/core/compat.py is the single version-tolerant source.
    Ported to tpu-lint (rule ``layer-shard-map``, AST-based so strings/
    comments can't false-positive) — this is a thin assert over the
    suite-shared analysis run."""
    from paddle_tpu import analysis
    bad = analysis.cached_report().new_for_rule("layer-shard-map")
    assert not bad, (
        "direct jax shard_map usage:\n" + "\n".join(f.text() for f in bad)
        + "\nimport it from paddle_tpu.core.compat instead")


# ---------------------------------------------------------------------------
# regressions (ISSUE 8, tpu-lint metric-contract / private-engine)
# ---------------------------------------------------------------------------

def test_all_settable_gauges_declared_at_construction():
    """Every gauge family set_gauge() may touch is on /metrics from the
    moment the sink exists — the scrape schema must not depend on which
    code paths (SLO breach, prefix cache) have run yet. tpu-lint's
    metric-contract rule flagged slo_breached and the live/cached page
    splits as minted-on-first-use; they are declared now."""
    m = ServingMetrics(namespace="paddle_serving_decl_test")
    for gauge in ("slo_breached", "live_page_utilization",
                  "cached_page_utilization"):
        assert gauge in m.gauges, gauge
    text = m.to_prometheus_text()
    for family in ("paddle_serving_decl_test_slo_breached_gauge",
                   "paddle_serving_decl_test_live_page_utilization_gauge",
                   "paddle_serving_decl_test_cached_page_utilization_gauge"):
        assert family in text, family


def test_scheduler_admission_uses_public_engine_queue_depth():
    """The scheduler's headroom math goes through the public
    ``engine.num_queued`` (tpu-lint private-engine: serving code must
    not reach into ``engine._queue``)."""
    cfg, params, eng, sched, _ = _setup(num_slots=2)
    assert eng.num_queued == 0
    for p in _prompts(cfg, 3, rng_seed=42):
        eng.submit(p)                    # 3rd waits in the engine FIFO
    assert eng.num_queued == len(eng._queue)
    assert eng.num_queued >= 1
