"""tpu-lint v2 (analysis/dataflow.py) — ISSUE 15 tier-1 suite.

Four layers:

* **CFG meta-tests** — loops carry back edges, try/finally duplicates
  the finally body onto the exception path, early returns reach the
  exit, handler edges exist and a non-catch-all handler still lets the
  exception continue out;
* **fixpoint/termination** — the worklist solver converges on loops and
  the interprocedural summaries terminate on cyclic call graphs;
* **per-rule synthetic violations + suppression/baseline semantics** —
  page-leak / dtype-flow / cache-key each catch a planted bug, stay
  quiet on the sanctioned shapes, and honor ``# tpu-lint: disable=`` +
  baseline fingerprints like every other family;
* **triage regressions** — the three genuine defects the first run of
  the new families surfaced stay fixed: the admission window leaking
  pages on exception, the kernel-backend flags missing from the
  compile-cache keys, and the quantized training layer widening the
  residual carry to f32.
"""

import ast
import json
import os
import subprocess
import textwrap
import time
from contextlib import contextmanager

import numpy as np
import pytest

from paddle_tpu import analysis
from paddle_tpu.analysis import (AnalysisEngine, Baseline, Project,
                                 default_rules)
from paddle_tpu.analysis.dataflow import (DATAFLOW_RULES, Summaries,
                                          build_cfg, solve_forward)

RULES_BY_ID = {r.id: r for r in default_rules()}
NEW_FAMILIES = ("page-leak", "dtype-flow", "cache-key")


def _run(tmp_path, files, rule_ids):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    proj = Project(tmp_path)
    rules = [RULES_BY_ID[r] for r in rule_ids]
    return AnalysisEngine(rules, Baseline()).run(proj)


def _cfg_of(src, name="f"):
    tree = ast.parse(textwrap.dedent(src))
    fn = next(n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef) and n.name == name)
    return build_cfg(fn)


def _reachable(block):
    seen, queue = set(), [block]
    while queue:
        b = queue.pop()
        if b.bid in seen:
            continue
        seen.add(b.bid)
        queue.extend(b.succ)
        queue.extend(b.esucc)
    return seen


# ---------------------------------------------------------------------------
# CFG construction meta-tests
# ---------------------------------------------------------------------------

def test_cfg_loop_has_back_edge_and_exit():
    cfg = _cfg_of("""
        def f(xs):
            total = 0
            for x in xs:
                total += x
            return total
    """)
    back = [(b, s) for b in cfg.blocks for s in b.succ if s.bid < b.bid]
    assert back, "loop produced no back edge"
    assert cfg.exit.bid in _reachable(cfg.entry)


def test_cfg_early_return_reaches_exit_and_kills_fallthrough():
    cfg = _cfg_of("""
        def f(x):
            if x:
                return 1
            return 2
    """)
    returns = [b for b in cfg.blocks
               if isinstance(b.stmt, ast.Return)]
    assert len(returns) == 2
    for r in returns:
        assert cfg.exit in r.succ
        assert not any(s.kind == "stmt" for s in r.succ)


def test_cfg_try_finally_duplicates_finally_on_exception_path():
    cfg = _cfg_of("""
        def f(mgr):
            mgr.acquire()
            try:
                risky()
            finally:
                mgr.release()
    """)
    release_blocks = [
        b for b in cfg.blocks
        if b.stmt is not None and "release" in ast.dump(b.stmt)]
    # at least two instances: the normal continuation and the
    # exception-path copy (whose tail re-raises into exc_exit)
    assert len(release_blocks) >= 2
    risky = next(b for b in cfg.blocks
                 if b.stmt is not None and "risky" in ast.dump(b.stmt))
    assert risky.esucc, "call in try body has no exception edge"
    exc_reach = _reachable(risky.esucc[0])
    assert cfg.exc_exit.bid in exc_reach
    assert any(b.bid in exc_reach for b in release_blocks), \
        "exception path bypasses the finally body"


def test_cfg_except_handler_edge_and_propagation():
    cfg = _cfg_of("""
        def f(mgr):
            try:
                risky()
            except MemoryError:
                fallback()
    """)
    risky = next(b for b in cfg.blocks
                 if b.stmt is not None and "risky" in ast.dump(b.stmt))
    # the handler is reachable along the exception edge...
    assert any("fallback" in ast.dump(s.stmt)
               for t in risky.esucc for s in _iter_blocks(cfg, t)
               if s.stmt is not None)
    # ...and a non-MemoryError exception still propagates out
    assert cfg.exc_exit.bid in _reachable(risky)


def _iter_blocks(cfg, start):
    return [b for b in cfg.blocks if b.bid in _reachable(start)]


def test_cfg_with_block_and_while():
    cfg = _cfg_of("""
        def f(lock, xs):
            with lock:
                while xs:
                    xs.pop()
            return xs
    """)
    assert cfg.exit.bid in _reachable(cfg.entry)
    back = [(b, s) for b in cfg.blocks for s in b.succ if s.bid < b.bid]
    assert back


def test_solver_converges_on_loops():
    cfg = _cfg_of("""
        def f(mgr, rid, xs):
            for x in xs:
                pages = mgr.allocate(rid, x)
                mgr.free(rid)
            return None
    """)

    class Count:
        def initial(self):
            return frozenset()

        def join(self, a, b):
            if a is None:
                return b
            if b is None:
                return a
            return a | b

        def transfer(self, state, block):
            if block.stmt is not None:
                state = state | {type(block.stmt).__name__}
            return state, state

    t0 = time.perf_counter()
    states = solve_forward(cfg, Count())
    assert time.perf_counter() - t0 < 1.0
    assert cfg.exit.bid in states


def test_summaries_terminate_on_cyclic_call_graph(tmp_path):
    (tmp_path / "paddle_tpu").mkdir(parents=True)
    (tmp_path / "paddle_tpu" / "cyc.py").write_text(textwrap.dedent("""
        from paddle_tpu.flags import flag_value

        def a(mgr, rid):
            return b(mgr, rid)

        def b(mgr, rid):
            if rid:
                return a(mgr, rid - 1)
            mgr.free(rid)
            return flag_value("cyc_flag")
    """))
    proj = Project(tmp_path)
    summaries = Summaries(proj.index)
    mi = proj.index.by_rel["paddle_tpu/cyc.py"]
    fa = mi.top_level["a"]
    t0 = time.perf_counter()
    assert summaries.releases(fa) is True       # through the cycle
    assert "cyc_flag" in summaries.flags_read(fa)
    assert time.perf_counter() - t0 < 1.0


def test_summaries_cycle_cut_results_are_not_poisoned(tmp_path):
    """Review fix (PR 15): a walk that hits the cycle cut computes a
    PROVISIONAL under-approximation — memoizing it poisoned every later
    query (the mutually-recursive helper that does release stayed
    "no-release" forever, minting page-leak false positives). The query
    ORDER matters: ``a`` first, so ``b`` is evaluated under the cut."""
    (tmp_path / "paddle_tpu").mkdir(parents=True)
    (tmp_path / "paddle_tpu" / "cyc2.py").write_text(textwrap.dedent("""
        from paddle_tpu.flags import flag_value

        def a(mgr, rid, n):
            if n:
                return b(mgr, rid, n - 1)
            return helper(mgr, rid)

        def b(mgr, rid, n):
            if n:
                return a(mgr, rid, n - 1)

        def helper(mgr, rid):
            mgr.free(rid)
            return flag_value("cyc2_flag")
    """))
    proj = Project(tmp_path)
    summaries = Summaries(proj.index)
    mi = proj.index.by_rel["paddle_tpu/cyc2.py"]
    fa, fb = mi.top_level["a"], mi.top_level["b"]
    assert summaries.releases(fa) is True
    # b releases through a -> helper; before the fix the a-walk memoized
    # b as False at the cut and this query returned the poisoned value
    assert summaries.releases(fb) is True
    assert "cyc2_flag" in summaries.flags_read(fa)
    assert "cyc2_flag" in summaries.flags_read(fb)


# ---------------------------------------------------------------------------
# page-leak synthetics
# ---------------------------------------------------------------------------

_LEAK_HEADER = "import jax\n"


@pytest.mark.parametrize("src,expect", [
    # plain leak: acquired, never released, never escapes
    ("""
     def f(mgr, rid):
         mgr.allocate(rid, 64)
         return None
     """, True),
    # exception-edge-only leak: the call between acquire and the
    # ownership transfer can raise with the pages still held
    ("""
     def f(mgr, rid, sink):
         pages = mgr.allocate(rid, 64)
         risky()
         sink.append(pages)
     """, True),
    # clean: try/finally releases on every path
    ("""
     def f(mgr, rid):
         mgr.allocate(rid, 64)
         try:
             risky()
         finally:
             mgr.free(rid)
     """, False),
    # clean: exception handler releases and re-raises
    ("""
     def f(mgr, rid, sink):
         pages = mgr.allocate(rid, 64)
         try:
             risky()
         except BaseException:
             mgr.free(rid)
             raise
         sink.append(pages)
     """, False),
    # clean: the result escapes immediately (ownership transfer)
    ("""
     def f(mgr, rid):
         return mgr.allocate(rid, 64)
     """, False),
    # clean: interprocedural release through a helper's summary
    ("""
     def cleanup(mgr, rid):
         mgr.free(rid)

     def f(mgr, rid):
         mgr.allocate(rid, 64)
         cleanup(mgr, rid)
     """, False),
    # clean: pool constructed in this frame dies with the frame
    ("""
     def f(rid):
         mgr = PagedKVCacheManager(1, 8, 4, 1, 8)
         mgr.allocate(rid, 64)
         risky()
     """, False),
    # clean: rollback via truncate_pages counts as a release
    ("""
     def f(mgr, rid):
         mgr.grow_to(rid, 128)
         try:
             risky()
         finally:
             mgr.truncate_pages(rid, 2)
     """, False),
    # clean: finally nested inside try/except — the exception continues
    # past the finally INTO the enclosing handler, which releases (CFG
    # _exc_targets regression: routing propagation only through outer
    # finallys skipped enclosing handlers and minted a false positive)
    ("""
     def f(mgr, rid, sink):
         try:
             pages = mgr.allocate(rid, 64)
             try:
                 risky()
             finally:
                 tick()
             sink.append(pages)
         except Exception:
             mgr.free(rid)
             raise
     """, False),
    # leak: same nesting but the enclosing handler never releases
    ("""
     def f(mgr, rid, sink):
         try:
             pages = mgr.allocate(rid, 64)
             try:
                 risky()
             finally:
                 tick()
             sink.append(pages)
         except ValueError:
             log()
             raise
     """, True),
    # clean: break leaves the loop THROUGH the enclosing finally, which
    # releases (CFG regression: break/continue jumped straight to the
    # loop exit, skipping finally bodies, and minted a false positive
    # on code that frees on every real path)
    ("""
     def f(mgr, reqs, sink):
         for r in reqs:
             try:
                 pages = mgr.allocate(r, 4)
                 if r > 3:
                     break
                 sink.append(pages)
             finally:
                 mgr.free(r)
     """, False),
    # clean: continue routes through the finally the same way
    ("""
     def f(mgr, reqs, sink):
         for r in reqs:
             try:
                 pages = mgr.allocate(r, 4)
                 if r > 3:
                     continue
                 sink.append(pages)
             finally:
                 mgr.free(r)
     """, False),
    # leak: without a finally, the break path really does bypass the
    # release (the jump edge itself must survive the finally routing)
    ("""
     def f(mgr, reqs):
         for r in reqs:
             mgr.allocate(r, 4)
             if r > 3:
                 break
             mgr.free(r)
     """, True),
])
def test_page_leak_synthetics(tmp_path, src, expect):
    rep = _run(tmp_path,
               {"paddle_tpu/inference/mod.py":
                _LEAK_HEADER + textwrap.dedent(src)},
               ["page-leak"])
    hits = rep.for_rule("page-leak")
    assert bool(hits) == expect, "\n".join(f.text() for f in hits)


def test_page_leak_scope_is_kvcache_and_inference_only(tmp_path):
    src = _LEAK_HEADER + textwrap.dedent("""
    def f(mgr, rid):
        mgr.allocate(rid, 64)
        return None
    """)
    rep = _run(tmp_path, {"paddle_tpu/serving/mod.py": src},
               ["page-leak"])
    assert not rep.for_rule("page-leak")


def test_page_leak_suppression_and_baseline(tmp_path):
    src = _LEAK_HEADER + textwrap.dedent("""
    def f(mgr, rid):
        mgr.allocate(rid, 64)  # tpu-lint: disable=page-leak
        return None

    def g(mgr, rid):
        mgr.allocate(rid, 64)
        return None
    """)
    rep = _run(tmp_path, {"paddle_tpu/kvcache/mod.py": src},
               ["page-leak"])
    hits = rep.for_rule("page-leak")
    assert len(hits) == 1 and "g" in hits[0].message
    fp = hits[0].fingerprint
    proj = Project(tmp_path)
    rep2 = AnalysisEngine([RULES_BY_ID["page-leak"]],
                          Baseline({fp: "known"})).run(proj)
    assert rep2.findings and not rep2.new and rep2.exit_code == 0


# ---------------------------------------------------------------------------
# dtype-flow synthetics
# ---------------------------------------------------------------------------

_DT_HEADER = """
    import jax
    import jax.numpy as jnp
"""


@pytest.mark.parametrize("body,kind,expect", [
    # mixed-dtype contraction: bf16 x f32 einsum, no explicit cast
    ("""
     a = x.astype(jnp.bfloat16)
     w = jnp.zeros((4, 4), jnp.float32)
     return jnp.einsum("ij,jk->ik", a, w)
     """, "mixed", True),
    # same contraction with the cast made explicit at the site: clean
    ("""
     a = x.astype(jnp.bfloat16)
     w = jnp.zeros((4, 4), jnp.float32)
     return jnp.einsum("ij,jk->ik", a.astype(jnp.float32), w)
     """, "mixed", False),
    # preferred_element_type chooses the accumulator: clean
    ("""
     a = x.astype(jnp.bfloat16)
     w = jnp.zeros((4, 4), jnp.float32)
     return jnp.dot(a, w, preferred_element_type=jnp.float32)
     """, "mixed", False),
    # silent arithmetic promotion bf16 + f32
    ("""
     a = x.astype(jnp.bfloat16)
     b = jnp.zeros((4, 4), jnp.float32)
     return a + b
     """, "promote", True),
    # dequant without scale reaching a contraction
    ("""
     q = jnp.zeros((4, 4), jnp.int8)
     deq = q.astype(jnp.float32)
     return jnp.einsum("ij,jk->ik", deq, deq)
     """, "dequant", True),
    # dequant WITH its scale multiply: clean
    ("""
     q = jnp.zeros((4, 4), jnp.int8)
     deq = q.astype(jnp.float32) * scale
     return jnp.einsum("ij,jk->ik", deq, deq)
     """, "dequant", False),
])
def test_dtype_flow_synthetics(tmp_path, body, kind, expect):
    indented = textwrap.indent(textwrap.dedent(body), "        ")
    src = _DT_HEADER + f"""
    def build():
        def run(x, scale):
{textwrap.indent(indented, "    ")}
        return jax.jit(run)
    """
    rep = _run(tmp_path, {"paddle_tpu/ops/mod.py": src}, ["dtype-flow"])
    hits = [f for f in rep.for_rule("dtype-flow")
            if f.symbol.endswith(f":{kind}")]
    assert bool(hits) == expect, "\n".join(
        f.text() for f in rep.for_rule("dtype-flow"))


def test_dtype_flow_scope_is_traced_ops_models_only(tmp_path):
    src = _DT_HEADER + """
    def run(x):
        a = x.astype(jnp.bfloat16)
        w = jnp.zeros((4, 4), jnp.float32)
        return jnp.einsum("ij,jk->ik", a, w)
    """
    # not reachable from any jit/pallas root -> out of scope
    rep = _run(tmp_path, {"paddle_tpu/ops/mod.py": src}, ["dtype-flow"])
    assert not rep.for_rule("dtype-flow")
    # traced but outside ops//models/ -> out of scope
    src2 = _DT_HEADER + """
    def build():
        def run(x):
            a = x.astype(jnp.bfloat16)
            w = jnp.zeros((4, 4), jnp.float32)
            return jnp.einsum("ij,jk->ik", a, w)
        return jax.jit(run)
    """
    rep2 = _run(tmp_path, {"paddle_tpu/serving/mod.py": src2},
                ["dtype-flow"])
    assert not rep2.for_rule("dtype-flow")


# ---------------------------------------------------------------------------
# cache-key synthetics
# ---------------------------------------------------------------------------

_CK_ENGINE = """
    import jax
    from paddle_tpu.flags import flag_value

    def _flags():
        return (bool(flag_value("mode_flag")),)

    class Eng:
        def __init__(self):
            self._compiled = {}
            self._one_shot = None

        def _build(self):
            def run(x):
                if flag_value("mode_flag"):
                    return x * 2
                return x
            return jax.jit(run)

        def step(self, bucket, x):
            key = %s
            if key not in self._compiled:
                self._compiled[key] = self._build()
            return self._compiled[key](x)
"""


def test_cache_key_missing_flag_is_flagged(tmp_path):
    rep = _run(tmp_path, {
        "paddle_tpu/inference/eng.py": _CK_ENGINE % "(bucket,)",
    }, ["cache-key"])
    hits = rep.for_rule("cache-key")
    assert len(hits) == 1
    assert "mode_flag" in hits[0].message
    assert hits[0].symbol.endswith(":self._compiled:mode_flag")


def test_cache_key_flag_derived_via_helper_is_clean(tmp_path):
    rep = _run(tmp_path, {
        "paddle_tpu/inference/eng.py": _CK_ENGINE % "(bucket,) + _flags()",
    }, ["cache-key"])
    assert not rep.for_rule("cache-key")


def test_cache_key_unguarded_one_time_build_is_not_a_cache(tmp_path):
    rep = _run(tmp_path, {"paddle_tpu/inference/eng2.py": """
        import jax
        from paddle_tpu.flags import flag_value

        class Eng:
            def _build(self):
                def run(x):
                    if flag_value("mode_flag"):
                        return x * 2
                    return x
                return jax.jit(run)

            def prime(self):
                # one-time unguarded build: trace-host-state's problem
                # (the read is still flagged there), not a cache-key one
                self._step = self._build()
    """}, ["cache-key"])
    assert not rep.for_rule("cache-key")


def test_cache_key_attribute_cache_with_freshness_guard(tmp_path):
    src = """
        import jax
        from paddle_tpu.flags import flag_value

        class Eng:
            def __init__(self):
                self._step = None

            def _build(self):
                def run(x):
                    if flag_value("mode_flag"):
                        return x * 2
                    return x
                return jax.jit(run)

            def step(self, x):
                if self._step is None:
                    self._step = self._build()
                return self._step(x)
    """
    rep = _run(tmp_path, {"paddle_tpu/inference/eng3.py": src},
               ["cache-key"])
    hits = rep.for_rule("cache-key")
    assert len(hits) == 1 and "mode_flag" in hits[0].message


def test_dtype_and_cache_key_suppression(tmp_path):
    """The shared disable=/baseline machinery covers the new families
    exactly like the PR 8 ones — same line scoping, same rule-id
    matching."""
    src = _DT_HEADER + """
    def build():
        def run(x):
            a = x.astype(jnp.bfloat16)
            w = jnp.zeros((4, 4), jnp.float32)
            # tpu-lint: disable=dtype-flow
            return jnp.einsum("ij,jk->ik", a, w)
        return jax.jit(run)
    """
    rep = _run(tmp_path, {"paddle_tpu/ops/mod.py": src}, ["dtype-flow"])
    assert not rep.for_rule("dtype-flow")

    eng = (_CK_ENGINE % "(bucket,)").replace(
        "self._compiled[key] = self._build()",
        "self._compiled[key] = self._build()"
        "  # tpu-lint: disable=cache-key")
    rep2 = _run(tmp_path, {"paddle_tpu/inference/eng.py": eng},
                ["cache-key"])
    assert not rep2.for_rule("cache-key")


# ---------------------------------------------------------------------------
# whole-package: new families clean + budget
# ---------------------------------------------------------------------------

def test_new_families_clean_on_tree_and_inside_budget():
    """The three dataflow families alone run the real tree inside the
    whole-package budget and come back clean against the baseline (the
    all-rules <5 s assertion lives in test_static_analysis)."""
    t0 = time.perf_counter()
    rep = analysis.run_repo(rules=list(DATAFLOW_RULES))
    elapsed = time.perf_counter() - t0
    # same 1-core-container allowance as test_static_analysis's budget
    budget = 5.0 if (os.cpu_count() or 1) > 1 else 10.0
    assert elapsed < budget, f"dataflow rules took {elapsed:.2f}s"
    assert not rep.new, "\n".join(f.text() for f in rep.new)
    assert not rep.stale
    # the deliberate speculative grow_to is baselined WITH a reason
    base = analysis.Baseline.load(analysis.BASELINE_PATH)
    leak_entries = {fp: why for fp, why in base.entries.items()
                    if ":page-leak:" in fp}
    assert leak_entries and all(why for why in leak_entries.values())


# ---------------------------------------------------------------------------
# CLI: SARIF + --changed-only
# ---------------------------------------------------------------------------

def test_sarif_output(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import main
    bad = tmp_path / "paddle_tpu" / "x.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import http.server\n")
    rc = main(["--root", str(tmp_path), "--no-baseline",
               "--rules", "layer-http", "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "tpu-lint"
    (result,) = run["results"]
    assert result["ruleId"] == "layer-http"
    assert result["level"] == "error"
    assert result["locations"][0]["physicalLocation"][
        "artifactLocation"]["uri"] == "paddle_tpu/x.py"
    assert result["partialFingerprints"]["tpuLint/v1"].startswith(
        "paddle_tpu/x.py:layer-http:")


def _git(root, *args):
    subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                    *args], cwd=root, check=True, capture_output=True)


def test_changed_only_scopes_to_diff_plus_reverse_deps(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import main
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("X = 1\n")
    (pkg / "b.py").write_text("from paddle_tpu.a import X\n"
                              "import http.server\n")
    (pkg / "c.py").write_text("import socket\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "a.py").write_text("X = 2\n")    # only a.py changes
    rc = main(["--root", str(tmp_path), "--no-baseline",
               "--changed-only", "HEAD", "--format", "json"])
    out = capsys.readouterr()
    doc = json.loads(out.out)
    files = {f["file"] for f in doc["findings"]}
    # b.py rides along (reverse dependency of the changed a.py); c.py's
    # socket violation is out of scope for this run
    assert "paddle_tpu/b.py" in files
    assert "paddle_tpu/c.py" not in files
    assert rc == 1
    assert "2 file(s)" in out.err


def test_changed_only_clean_diff_is_fast_and_green(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import main
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("X = 1\n")
    (pkg / "c.py").write_text("import socket\n")   # pre-existing, untouched
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    t0 = time.perf_counter()
    rc = main(["--root", str(tmp_path), "--no-baseline",
               "--changed-only", "HEAD"])
    assert time.perf_counter() - t0 < 1.0   # the pre-commit contract
    capsys.readouterr()
    assert rc == 0


def test_changed_only_closure_includes_package_inits(tmp_path):
    """A package ``__init__.py``'s one-dot relative import refers to the
    package ITSELF (its modname already is the package), so re-exporting
    __init__ files must land in the reverse-dependency closure —
    before the fix the base resolved one level too high and they were
    silently skipped by pre-commit runs."""
    from paddle_tpu.analysis.__main__ import changed_closure
    pkg = tmp_path / "paddle_tpu" / "sub"
    pkg.mkdir(parents=True)
    (tmp_path / "paddle_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("from .engine import X\n")
    (pkg / "engine.py").write_text("X = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "engine.py").write_text("X = 2\n")
    closure = changed_closure(tmp_path, ("paddle_tpu",), "HEAD")
    assert "paddle_tpu/sub/engine.py" in closure
    assert "paddle_tpu/sub/__init__.py" in closure


def test_changed_only_closure_includes_bare_relative_imports(tmp_path):
    """``from . import format as fmt`` depends on the SUBMODULE, not
    just the package — before the fix only the bare package name was
    recorded, so a change to ``format.py`` left this dependent out of
    the closure and a pre-commit run could report clean with a new
    finding in it."""
    from paddle_tpu.analysis.__main__ import changed_closure
    pkg = tmp_path / "paddle_tpu" / "obs"
    pkg.mkdir(parents=True)
    (tmp_path / "paddle_tpu" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "format.py").write_text("X = 1\n")
    (pkg / "registry.py").write_text("from . import format as fmt\n")
    # absolute form of the same gap: the submodule, not the package,
    # is the dependency
    (pkg / "server.py").write_text("from paddle_tpu.obs import format\n")
    (pkg / "other.py").write_text("Y = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "format.py").write_text("X = 2\n")
    closure = changed_closure(tmp_path, ("paddle_tpu",), "HEAD")
    assert "paddle_tpu/obs/registry.py" in closure
    assert "paddle_tpu/obs/server.py" in closure
    assert "paddle_tpu/obs/other.py" not in closure


def test_changed_only_root_below_git_toplevel(tmp_path):
    """Review fix (PR 15): ``git diff --name-only`` emits toplevel-
    relative paths; without ``--relative`` a --root below the toplevel
    matched nothing and the scoped run silently analyzed (almost)
    nothing with exit 0."""
    from paddle_tpu.analysis.__main__ import changed_closure
    root = tmp_path / "checkout"
    pkg = root / "paddle_tpu"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("X = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "a.py").write_text("X = 2\n")
    closure = changed_closure(root, ("paddle_tpu",), "HEAD")
    assert closure == {"paddle_tpu/a.py"}


def test_changed_only_includes_untracked_files(tmp_path):
    """Brand-new files never show in ``git diff --name-only REF`` until
    staged — the pre-commit mode must still analyze them (before the
    fix a leak in a new file reported a clean 0-finding run)."""
    from paddle_tpu.analysis.__main__ import changed_closure
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("X = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "fresh.py").write_text("import socket\n")   # untracked
    closure = changed_closure(tmp_path, ("paddle_tpu",), "HEAD")
    assert "paddle_tpu/fresh.py" in closure
    assert "paddle_tpu/a.py" not in closure


def test_int_promotion_uses_widths_not_lexicographic():
    """int8 x int16 promotes to int16 (lexicographic comparison said
    int8); equal-width signed/unsigned mixes (numpy: int16) and unknown
    tokens fall to TOP — an unknown dtype only loses recall, a wrong
    one mints false mixed-dtype findings downstream."""
    from paddle_tpu.analysis.dataflow import TOP, _promote
    assert _promote("int8", "int16") == "int16"
    assert _promote("int16", "int8") == "int16"
    assert _promote("uint8", "int64") == "int64"
    assert _promote("int8", "uint8") is TOP
    assert _promote("int8", "bool") is TOP
    assert _promote("bfloat16", "int8") == "bfloat16"


def test_changed_only_rejects_write_baseline(tmp_path, capsys):
    from paddle_tpu.analysis.__main__ import main
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir(parents=True)
    (pkg / "a.py").write_text("X = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    rc = main(["--root", str(tmp_path), "--changed-only", "HEAD",
               "--write-baseline"])
    capsys.readouterr()
    assert rc == 2


# ---------------------------------------------------------------------------
# triage regressions: one genuine defect per family stays fixed
# ---------------------------------------------------------------------------

def _tiny_engine(**over):
    from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                               GenerationConfig)
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny(num_hidden_layers=1)
    kw = dict(num_slots=2, page_size=4, max_seq_len=32, chunk=4)
    kw.update(over)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=4, seed=0), **kw)
    params = L.init_stacked_params(cfg, seed=0)
    return eng, params


@contextmanager
def _ledger_boom(fail_at=1):
    """Arm the memory ledger with a note_request that raises on the
    ``fail_at``-th call — a REAL in-window raise site of _admit_window
    (between allocate and the slot hand-off)."""
    from paddle_tpu.observability.memory import memory_ledger
    calls = {"n": 0}
    orig = memory_ledger.note_request

    def boom(*a, **k):
        calls["n"] += 1
        if calls["n"] >= fail_at:
            raise RuntimeError("injected admission fault")
        return orig(*a, **k)

    memory_ledger.reset()
    memory_ledger.arm()
    memory_ledger.note_request = boom
    try:
        yield
    finally:
        memory_ledger.note_request = orig
        memory_ledger.disarm()
        memory_ledger.reset()


def test_admission_failure_frees_pages_and_requeues():
    """page-leak triage (PR 15): anything raising between allocate and
    the slot hand-off in _admit_pick must return the pages and requeue
    the request — before the fix the pages leaked and the request was
    silently dropped."""
    eng, params = _tiny_engine(prefix_cache=True)
    prompt = np.arange(1, 9, dtype=np.int32)
    rid = eng.submit(prompt)

    with _ledger_boom():
        with pytest.raises(RuntimeError, match="injected"):
            eng.step(params)
        eng.mgr.check_conservation()        # no page left behind
        assert eng.num_queued == 1          # the request survived
    for _ in range(64):
        eng.step(params)
        if rid in eng._finished:
            break
    assert rid in eng._finished and len(eng._finished[rid]) == 4
    eng.mgr.check_conservation()


def test_admission_failure_rolls_back_every_picked_request():
    """Review fix (PR 15): the admission rollback covers the WHOLE
    window, not just the current iteration — with two requests picked
    into two slots in one step, a raise during the window frees BOTH
    allocations and requeues both; before the fix only the in-flight
    request was rolled back while the earlier pick's pages leaked
    (never reaching _slot_rid, invisible to cancel/retire) and its
    request silently vanished."""
    eng, params = _tiny_engine(prefix_cache=True)
    p1 = np.arange(1, 9, dtype=np.int32)
    p2 = np.arange(3, 11, dtype=np.int32)
    r1 = eng.submit(p1)
    r2 = eng.submit(p2)

    with _ledger_boom(fail_at=2):           # both picked, then raise
        with pytest.raises(RuntimeError, match="injected"):
            eng.step(params)
        eng.mgr.check_conservation()        # no page left behind
        assert eng.num_queued == 2          # BOTH requests survived
    for _ in range(64):
        eng.step(params)
        if r1 in eng._finished and r2 in eng._finished:
            break
    assert len(eng._finished[r1]) == 4
    assert len(eng._finished[r2]) == 4
    eng.mgr.check_conservation()


def test_stats_sink_failure_does_not_abort_admission():
    """Review fix (PR 15): cache.record is stats-only and runs AFTER
    the admission window commits — a broken sink must neither tear the
    window down (rolling back would re-admit and double-count the hits
    already recorded) nor leak pages; the serve completes normally."""
    eng, params = _tiny_engine(prefix_cache=True)
    prompt = np.arange(1, 9, dtype=np.int32)
    rid = eng.submit(prompt)

    orig = eng.cache.record
    eng.cache.record = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("broken stats sink"))
    try:
        eng.step(params)                    # must NOT raise
    finally:
        eng.cache.record = orig
    assert eng.num_queued == 0              # admission stuck
    for _ in range(64):
        eng.step(params)
        if rid in eng._finished:
            break
    assert rid in eng._finished and len(eng._finished[rid]) == 4
    eng.mgr.check_conservation()


def test_backend_flag_flip_retraces_unified_step():
    """cache-key triage (PR 15): the kernel-backend selectors
    (use_pallas_kernels / use_pallas_rms_norm) are read at trace time,
    so every guarding compile-cache key must derive from them — a
    set_flags flip now rebuilds the unified program as a counted
    recompile instead of silently serving the old backend."""
    from paddle_tpu.flags import get_flags, set_flags
    eng, params = _tiny_engine()
    eng.submit(np.arange(1, 6, dtype=np.int32))
    eng.step(params)
    first = eng._unified_step
    flags0 = eng._unified_flags
    assert first is not None and len(flags0) == 3
    saved = get_flags("use_pallas_rms_norm")
    try:
        set_flags({"use_pallas_rms_norm":
                   not saved["use_pallas_rms_norm"]})
        eng.step(params)
        assert eng._unified_flags != flags0
        assert eng._unified_step is not first   # retraced, not stale
    finally:
        set_flags(saved)


def test_quantized_training_layer_keeps_residual_carry_dtype():
    """dtype-flow triage (PR 15): _decoder_layer_manual (the shard_map
    training layer) silently widened the residual stream to f32 when
    weights are int8-quantized dicts (weight_dequantize returns f32) —
    the serving scan paths pin the carry dtype and now the training
    layer does too. Dense weights are untouched (the cast is a no-op)."""
    import jax.numpy as jnp
    from paddle_tpu.models import llama as L
    from paddle_tpu.ops import rope as rope_ops

    cfg = L.llama_tiny(num_hidden_layers=1)
    params = L.init_stacked_params(cfg, seed=0)
    p = {k: v[0] for k, v in params["layers"].items()} \
        if "layers" in params else None
    if p is None:
        # stacked layout keys live at the top level with a leading L axis
        names = ("ln1", "wq", "wk", "wv", "wo", "ln2", "w_gate", "w_up",
                 "w_down")
        p = {k: params[k][0] for k in names}

    def quantize(w):
        w = np.asarray(w, np.float32)
        scale = np.abs(w).max(axis=0) / 127.0 + 1e-8
        q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        return {"q": jnp.asarray(q), "scale": jnp.asarray(scale)}

    pq = dict(p)
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        pq[k] = quantize(p[k])
    x = jnp.ones((1, 4, cfg.hidden_size), jnp.bfloat16)
    cos, sin = rope_ops.build_rope_cache(4, cfg.head_dim, cfg.rope_theta)
    out_q = L._decoder_layer_manual(pq, x, cos, sin, cfg, None, None)
    assert out_q.dtype == jnp.bfloat16, (
        "quantized weights widened the residual carry to "
        f"{out_q.dtype}")
    out_d = L._decoder_layer_manual(
        {k: jnp.asarray(v, jnp.bfloat16) if k.startswith(("w", "ln"))
         else v for k, v in p.items()}, x, cos, sin, cfg, None, None)
    assert out_d.dtype == jnp.bfloat16
