"""Async checkpoint + TrainState capture/restore (SURVEY.md §5.4)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.checkpoint import (
    async_save_state_dict, load_state_dict, TrainState,
)


def _net():
    paddle.seed(21)
    return nn.Sequential(nn.Linear(3, 8), nn.ReLU(), nn.Linear(8, 2))


def test_async_save_then_load(tmp_path):
    net = _net()
    sd = net.state_dict()
    fut = async_save_state_dict(sd, str(tmp_path / "ck"))
    path = fut.result(timeout=60)
    assert fut.done()

    net2 = _net()
    # perturb then restore
    for p in net2.parameters():
        p.set_value(np.zeros(p.shape, np.float32))
    target = net2.state_dict()
    load_state_dict(target, path)
    net2.set_state_dict(target)
    for a, b in zip(net.parameters(), net2.parameters()):
        np.testing.assert_allclose(np.asarray(a._value), np.asarray(b._value))


@pytest.mark.slow
def test_async_save_snapshot_isolated_from_mutation(tmp_path):
    """Mutating params after async_save must not corrupt the checkpoint
    (the snapshot is taken synchronously)."""
    net = _net()
    w0 = np.asarray(net[0].weight._value).copy()
    fut = async_save_state_dict(net.state_dict(), str(tmp_path / "ck2"))
    net[0].weight.set_value(np.full_like(w0, 7.0))  # mutate immediately
    path = fut.result(60)
    target = _net().state_dict()
    load_state_dict(target, path)
    key = [k for k in target if "weight" in k][0]
    np.testing.assert_allclose(np.asarray(target[key]._value
                                          if hasattr(target[key], "_value")
                                          else target[key]), w0)


def test_train_state_roundtrip(tmp_path):
    net = _net()
    opt = optimizer.AdamW(learning_rate=1e-2, parameters=net.parameters())
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=2)
    ts = TrainState(net, opt, sched)
    # do a couple of steps so optimizer state materialises
    x = paddle.to_tensor(np.ones((4, 3), np.float32))
    for _ in range(3):
        (net(x) ** 2).mean().backward()
        opt.step()
        opt.clear_grad()
        ts.step()
    ts.next_epoch()
    ts.step(2)
    fut = async_save_state_dict(ts.state_dict(), str(tmp_path / "ts"))
    fut.result(60)

    net2 = _net()
    opt2 = optimizer.AdamW(learning_rate=1e-2, parameters=net2.parameters())
    sched2 = optimizer.lr.StepDecay(learning_rate=0.1, step_size=2)
    ts2 = TrainState(net2, opt2, sched2)
    target = ts2.state_dict()
    load_state_dict(target, str(tmp_path / "ts"))
    ts2.set_state_dict(target)
    assert ts2.global_step == 5 and ts2.epoch == 1 and ts2.batch_in_epoch == 2
    for a, b in zip(net.parameters(), net2.parameters()):
        np.testing.assert_allclose(np.asarray(a._value), np.asarray(b._value),
                                   rtol=1e-6)


def test_skip_batches():
    from paddle_tpu import io
    ds = io.TensorDataset([np.arange(12, dtype=np.float32).reshape(12, 1)])
    loader = io.DataLoader(ds, batch_size=2)
    ts = TrainState()
    ts.batch_in_epoch = 2
    it = ts.skip_batches(loader)
    nxt = np.asarray(next(it))
    np.testing.assert_array_equal(nxt.ravel(), [4, 5])


def test_skip_batches_shuffled_resume():
    """Mid-epoch resume under shuffle replays the exact permutation."""
    from paddle_tpu import io
    ds = io.TensorDataset([np.arange(16, dtype=np.float32).reshape(16, 1)])
    loader = io.DataLoader(ds, batch_size=4, shuffle=True)
    loader.batch_sampler.set_epoch(3)
    seen = [np.asarray(b).ravel().tolist() for b in loader][:2]

    loader2 = io.DataLoader(ds, batch_size=4, shuffle=True)
    ts = TrainState()
    ts.epoch, ts.batch_in_epoch = 3, 2
    it = ts.skip_batches(loader2)
    third = np.asarray(next(it)).ravel().tolist()
    # the fresh loader pinned to epoch 3 must continue after `seen`
    loader3 = io.DataLoader(ds, batch_size=4, shuffle=True)
    loader3.batch_sampler.set_epoch(3)
    full3 = [np.asarray(b).ravel().tolist() for b in loader3]
    assert full3[:2] == seen and full3[2] == third


def test_failed_async_save_does_not_wedge(tmp_path, monkeypatch):
    import paddle_tpu.distributed.checkpoint.async_save as A
    calls = {"n": 0}
    real = A.save_state_dict

    def flaky(sd, path, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise IOError("disk full")
        return real(sd, path, **kw)

    monkeypatch.setattr(A, "save_state_dict", flaky)
    net = _net()
    f1 = A.async_save_state_dict(net.state_dict(), str(tmp_path / "a"))
    with pytest.raises(IOError):
        f1.result(30)
    # next save proceeds despite the earlier failure
    f2 = A.async_save_state_dict(net.state_dict(), str(tmp_path / "b"))
    assert f2.result(30)
