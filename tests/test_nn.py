"""nn.Layer + layers/functional tests."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_forward_backward():
    paddle.seed(0)
    lin = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    y = lin(x)
    assert y.shape == [2, 3]
    loss = y.sum()
    loss.backward()
    assert lin.weight.grad is not None
    assert lin.weight.grad.shape == [4, 3]
    assert lin.bias.grad.shape == [3]


def test_layer_registry_and_state_dict():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    sd = net.state_dict()
    net2 = Net()
    net2.set_state_dict(sd)
    x = paddle.randn([3, 4])
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    x = paddle.randn([2, 4])
    assert seq(x).shape == [2, 2]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    assert len(list(ll.parameters())) == 6


def test_conv2d_shapes():
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    y = conv(x)
    assert y.shape == [2, 8, 8, 8]
    y.sum().backward()
    assert conv.weight.grad is not None


def test_conv2d_matches_numpy():
    # 1x1 conv == per-pixel matmul
    conv = nn.Conv2D(2, 3, 1, bias_attr=False)
    x = paddle.randn([1, 2, 4, 4])
    y = conv(x).numpy()
    w = conv.weight.numpy().reshape(3, 2)
    ref = np.einsum("oc,bchw->bohw", w, x.numpy())
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.randn([8, 4, 2, 2])
    bn.train()
    y = bn(x)
    m = y.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(4), atol=1e-5)
    # running stats updated
    assert not np.allclose(bn._mean.numpy(), np.zeros(4))
    bn.eval()
    y2 = bn(x)
    assert y2.shape == [8, 4, 2, 2]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([4, 8])
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(y.std(-1), np.ones(4), atol=1e-2)


def test_rms_norm_matches_formula():
    rn = nn.RMSNorm(16)
    x = paddle.randn([3, 16])
    y = rn(x).numpy()
    xn = x.numpy()
    ref = xn / np.sqrt((xn ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)
    rn.weight._grad_value = None
    out = rn(x).sum()
    out.backward()
    assert rn.weight.grad is not None


def test_dropout_modes():
    x = paddle.ones([1000])
    d = nn.Dropout(0.5)
    d.train()
    y = d(x)
    frac = float((y.numpy() == 0).mean())
    assert 0.3 < frac < 0.7
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding():
    emb = nn.Embedding(10, 4)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], dtype=np.int64))
    out = emb(ids)
    assert out.shape == [2, 2, 4]
    out.sum().backward()
    assert emb.weight.grad is not None


def test_cross_entropy_matches_numpy():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor(np.array([0, 1, 2, 3], dtype=np.int64))
    loss = F.cross_entropy(logits, labels)
    ln = logits.numpy().astype(np.float64)
    p = np.exp(ln - ln.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(4), labels.numpy()]).mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_softmax_axis():
    x = paddle.randn([2, 3, 4])
    y = F.softmax(x, axis=1)
    np.testing.assert_allclose(y.numpy().sum(1), np.ones((2, 4)), rtol=1e-5)


def test_pooling():
    x = paddle.randn([1, 2, 8, 8])
    assert F.max_pool2d(x, 2).shape == [1, 2, 4, 4]
    assert F.avg_pool2d(x, 2, stride=2).shape == [1, 2, 4, 4]
    assert F.adaptive_avg_pool2d(x, 1).shape == [1, 2, 1, 1]


def test_sdpa_matches_reference():
    paddle.seed(1)
    q = paddle.randn([2, 8, 2, 16])
    k = paddle.randn([2, 8, 2, 16])
    v = paddle.randn([2, 8, 2, 16])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    qn, kn, vn = (t.numpy().astype(np.float64) for t in (q, k, v))
    s = np.einsum("bqhd,bkhd->bhqk", qn, kn) / np.sqrt(16)
    mask = np.tril(np.ones((8, 8), dtype=bool))
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, vn)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_sdpa_grads_flow():
    q = paddle.randn([1, 4, 1, 8])
    q.stop_gradient = False
    k = paddle.randn([1, 4, 1, 8])
    v = paddle.randn([1, 4, 1, 8])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    out.sum().backward()
    assert q.grad is not None and q.grad.shape == [1, 4, 1, 8]


def test_cross_entropy_ignore_index():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor(np.array([0, -100, 2, -100], dtype=np.int64))
    loss = F.cross_entropy(logits, labels)
    ln = logits.numpy().astype(np.float64)
    p = np.exp(ln - ln.max(-1, keepdims=True)); p /= p.sum(-1, keepdims=True)
    ref = -(np.log(p[0, 0]) + np.log(p[2, 2])) / 2
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_pad_partial_convention():
    x = paddle.ones([1, 1, 2, 3])
    y = F.pad(x, [1, 1, 0, 0])  # left/right on W only
    assert y.shape == [1, 1, 2, 5]
    y2 = F.pad(x, [0, 0, 2, 0])  # top pad on H
    assert y2.shape == [1, 1, 4, 3]


def test_dropout_downscale_in_infer():
    x = paddle.ones([10])
    y = F.dropout(x, p=0.4, training=False, mode="downscale_in_infer")
    np.testing.assert_allclose(y.numpy(), np.full(10, 0.6), rtol=1e-6)


class TestFoldGridSample:
    """fold / affine_grid / grid_sample (VERDICT op-family gaps)."""

    def test_fold_inverts_unfold_nonoverlapping(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
        cols = F.unfold(x, kernel_sizes=2, strides=2)
        back = F.fold(cols, output_sizes=(8, 8), kernel_sizes=2, strides=2)
        np.testing.assert_allclose(np.asarray(back._value),
                                   np.asarray(x._value), rtol=1e-6)

    def test_fold_sums_overlaps(self):
        x = paddle.to_tensor(np.ones((1, 1, 4, 4), np.float32))
        cols = F.unfold(x, kernel_sizes=3, strides=1)
        out = np.asarray(F.fold(cols, output_sizes=(4, 4), kernel_sizes=3,
                                strides=1)._value)
        # center pixels belong to 4 patches, corners to 1
        assert out[0, 0, 0, 0] == 1.0 and out[0, 0, 1, 1] == 4.0

    def test_affine_grid_identity_and_grid_sample(self):
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(2, 3, 5, 7).astype(np.float32))
        theta = paddle.to_tensor(
            np.tile(np.array([[1, 0, 0], [0, 1, 0]], np.float32), (2, 1, 1)))
        grid = F.affine_grid(theta, (2, 3, 5, 7), align_corners=True)
        out = F.grid_sample(x, grid, align_corners=True)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(x._value), rtol=1e-5,
                                   atol=1e-5)

    def test_grid_sample_nearest_and_zero_padding(self):
        x = paddle.to_tensor(
            np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        # sample far outside -> zeros padding
        grid = paddle.to_tensor(np.full((1, 2, 2, 2), 5.0, np.float32))
        out = F.grid_sample(x, grid, mode="nearest", padding_mode="zeros")
        np.testing.assert_allclose(np.asarray(out._value), 0.0)
        # border padding clamps
        outb = F.grid_sample(x, grid, mode="nearest", padding_mode="border")
        np.testing.assert_allclose(np.asarray(outb._value), 15.0)

    @pytest.mark.slow
    def test_grid_sample_grad_flows(self):
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(1, 2, 6, 6).astype(np.float32))
        x.stop_gradient = False
        theta = paddle.to_tensor(
            np.array([[[0.9, 0.1, 0.0], [0.0, 1.1, 0.1]]], np.float32))
        grid = F.affine_grid(theta, (1, 2, 6, 6))
        F.grid_sample(x, grid).sum().backward()
        assert x.grad is not None
        assert np.isfinite(np.asarray(x.grad._value)).all()
