"""Optimizer tests: update-rule oracles + convergence smoke."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _quadratic_param():
    p = paddle.creation.create_parameter((2,), dtype="float32",
                                         default_initializer=paddle.nn.initializer.Assign(
                                             np.array([5.0, -3.0], np.float32)))
    return p


def test_sgd_rule():
    p = _quadratic_param()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    loss = (p * p).sum()
    loss.backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [5 - 0.1 * 10, -3 + 0.1 * 6], rtol=1e-6)


def test_momentum_velocity():
    p = _quadratic_param()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
    for _ in range(2):
        (p * p).sum().backward()
        opt.step()
        opt.clear_grad()
    # hand computation
    w = np.array([5.0, -3.0])
    vel = np.zeros(2)
    for _ in range(2):
        g = 2 * w
        vel = 0.9 * vel + g
        w = w - 0.1 * vel
    np.testing.assert_allclose(p.numpy(), w, rtol=1e-5)


def test_adam_rule_matches_numpy():
    p = _quadratic_param()
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p])
    w = np.array([5.0, -3.0])
    m = np.zeros(2)
    v = np.zeros(2)
    for t in range(1, 4):
        (p * p).sum().backward()
        opt.step()
        opt.clear_grad()
        g = 2 * w
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** t)
        vh = v / (1 - 0.999 ** t)
        w = w - 0.01 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(p.numpy(), w, rtol=1e-5)


def test_adamw_decoupled_decay():
    p = _quadratic_param()
    opt = paddle.optimizer.AdamW(learning_rate=0.01, weight_decay=0.1,
                                 parameters=[p])
    (p * p).sum().backward()
    opt.step()
    w = np.array([5.0, -3.0])
    g = 2 * w
    mh = (0.1 * g) / (1 - 0.9)
    vh = (0.001 * g * g) / (1 - 0.999)
    w = w - 0.01 * (mh / (np.sqrt(vh) + 1e-8) + 0.1 * w)
    np.testing.assert_allclose(p.numpy(), w, rtol=1e-5)


def test_global_norm_clip():
    p = _quadratic_param()
    clip = paddle.nn_clip = paddle.optimizer.ClipGradByGlobalNorm(1.0)
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p], grad_clip=clip)
    (p * p).sum().backward()
    g = 2 * np.array([5.0, -3.0])
    gnorm = np.linalg.norm(g)
    expected = np.array([5.0, -3.0]) - g / gnorm
    opt.step()
    np.testing.assert_allclose(p.numpy(), expected, rtol=1e-5)


def test_lr_scheduler():
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    p = _quadratic_param()
    opt = paddle.optimizer.SGD(learning_rate=sched, parameters=[p])
    lrs = []
    for _ in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)


@pytest.mark.slow
def test_training_converges():
    paddle.seed(0)
    net = nn.Linear(4, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
    rng = np.random.RandomState(0)
    xs = rng.randn(64, 4).astype(np.float32)
    true_w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    ys = xs @ true_w + 0.7
    x = paddle.to_tensor(xs)
    y = paddle.to_tensor(ys)
    losses = []
    for _ in range(150):
        pred = net(x)
        loss = F.mse_loss(pred, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < 0.01, losses[-1]


def test_multi_precision_master_weights():
    p = paddle.creation.create_parameter((4,), dtype="float32")
    p._value = p._value.astype("bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=0.001, parameters=[p],
                                 multi_precision=True)
    (p.astype("float32") ** 2).sum().backward()
    opt.step()
    state = opt._accumulators[id(p)]
    assert "master" in state
    assert str(state["master"].dtype) == "float32"
    assert str(p._value.dtype) == "bfloat16"


def test_optimizer_state_dict_roundtrip():
    p = _quadratic_param()
    p.name = "w0"
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p])
    (p * p).sum().backward()
    opt.step()
    sd = opt.state_dict()
    p2 = _quadratic_param()
    p2.name = "w0"
    opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=[p2])
    opt2.set_state_dict(sd)
    np.testing.assert_allclose(
        np.asarray(opt2._accumulators[id(p2)]["moment1"]),
        np.asarray(opt._accumulators[id(p)]["moment1"]))
