"""Tensor + op surface tests (numpy-oracle style, SURVEY.md §4 OpTest model)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])


def test_arithmetic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b - a).numpy(), [3, 3, 3])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2], rtol=1e-6)
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4, 5])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])


def test_matmul():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose((a @ b).numpy(), a.numpy() @ b.numpy())
    c = paddle.matmul(a, a, transpose_y=True)
    np.testing.assert_allclose(c.numpy(), a.numpy() @ a.numpy().T)


def test_reductions():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(x.sum().numpy(), 66)
    np.testing.assert_allclose(x.mean(axis=0).numpy(), x.numpy().mean(0))
    np.testing.assert_allclose(x.max(axis=1, keepdim=True).numpy(),
                               x.numpy().max(1, keepdims=True))
    np.testing.assert_allclose(paddle.std(x).numpy(), x.numpy().std(ddof=1),
                               rtol=1e-6)


def test_manipulation():
    x = paddle.arange(24, dtype="float32").reshape([2, 3, 4])
    assert x.transpose([1, 0, 2]).shape == [3, 2, 4]
    assert paddle.concat([x, x], axis=1).shape == [2, 6, 4]
    assert paddle.stack([x, x]).shape == [2, 2, 3, 4]
    parts = paddle.split(x, 2, axis=2)
    assert len(parts) == 2 and parts[0].shape == [2, 3, 2]
    parts = paddle.split(x, [1, -1], axis=1)
    assert parts[0].shape == [2, 1, 4] and parts[1].shape == [2, 2, 4]
    assert x.flatten().shape == [24] or x.flatten(0, -1).shape == [24]
    assert paddle.unsqueeze(x, 0).shape == [1, 2, 3, 4]
    assert paddle.squeeze(paddle.ones([1, 3, 1]), axis=0).shape == [3, 1]


def test_indexing():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(x[1:, 2:].numpy(), [[6, 7], [10, 11]])
    x[0] = paddle.zeros([4])
    np.testing.assert_allclose(x[0].numpy(), [0, 0, 0, 0])


def test_comparison_and_where():
    a = paddle.to_tensor([1.0, 5.0, 3.0])
    b = paddle.to_tensor([4.0, 2.0, 3.0])
    np.testing.assert_array_equal((a > b).numpy(), [False, True, False])
    np.testing.assert_allclose(paddle.where(a > b, a, b).numpy(), [4, 5, 3])
    np.testing.assert_allclose(paddle.maximum(a, b).numpy(), [4, 5, 3])


def test_gather_topk_sort():
    x = paddle.to_tensor([3.0, 1.0, 4.0, 1.0, 5.0])
    vals, idx = paddle.topk(x, 2)
    np.testing.assert_allclose(vals.numpy(), [5, 4])
    np.testing.assert_array_equal(idx.numpy(), [4, 2])
    g = paddle.gather(x, paddle.to_tensor([0, 2]))
    np.testing.assert_allclose(g.numpy(), [3, 4])
    np.testing.assert_allclose(paddle.sort(x).numpy(), [1, 1, 3, 4, 5])


def test_cast_dtype():
    x = paddle.ones([2], dtype="float32")
    y = x.astype("bfloat16")
    assert str(y.dtype) == "bfloat16"
    z = y.astype(paddle.int32)
    assert z.numpy().dtype == np.int32


def test_creation_random_reproducible():
    paddle.seed(42)
    a = paddle.randn([4, 4])
    paddle.seed(42)
    b = paddle.randn([4, 4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    u = paddle.uniform([100], min=-2, max=2)
    assert float(u.min()) >= -2 and float(u.max()) <= 2


def test_einsum():
    a = paddle.randn([2, 3])
    b = paddle.randn([3, 4])
    out = paddle.einsum("ij,jk->ik", a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
