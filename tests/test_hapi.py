"""hapi Model API tests (reference test style: test/legacy_test/test_model.py)."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi.callbacks import (Callback, EarlyStopping, ModelCheckpoint,
                                       ReduceLROnPlateau, VisualDL)
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy

pytestmark = pytest.mark.slow  # core tier: -m 'not slow'


class XorDataset(Dataset):
    """Tiny separable problem: y = (x0 > 0) ^ (x1 > 0)."""

    def __init__(self, n=128, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 2).astype(np.float32)
        self.y = ((self.x[:, 0] > 0) ^ (self.x[:, 1] > 0)).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class XorInputs(XorDataset):
    """Inputs-only view (reference predict datasets carry no labels)."""

    def __getitem__(self, i):
        return self.x[i]


def _model():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(2, 32), nn.Tanh(), nn.Linear(32, 2))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
    return model


def test_fit_evaluate_predict(capsys):
    model = _model()
    history = model.fit(XorDataset(512), XorDataset(64, seed=1), batch_size=32,
                        epochs=20, verbose=0)
    assert len(history) == 20
    assert history[-1]["loss"] < history[0]["loss"]
    res = model.evaluate(XorDataset(64, seed=2), batch_size=32, verbose=0)
    assert res["acc"] > 0.9
    outs = model.predict(XorInputs(16, seed=3), batch_size=8,
                         stack_outputs=True, verbose=0)
    assert len(outs) == 1 and outs[0].shape == (16, 2)


def test_train_eval_batch():
    model = _model()
    x = np.random.RandomState(0).randn(8, 2).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    l0 = model.train_batch([x], [y])
    assert len(l0) == 1 and np.isfinite(l0[0])
    le = model.eval_batch([x], [y])
    assert len(le) == 1 and np.isfinite(le[0])
    p = model.predict_batch([x])
    assert p[0].shape == (8, 2)


def test_save_load(tmp_path):
    model = _model()
    model.fit(XorDataset(32), batch_size=16, epochs=1, verbose=0)
    path = str(tmp_path / "ckpt" / "m")
    model.save(path)
    assert os.path.exists(path + ".pdparams")
    assert os.path.exists(path + ".pdopt")
    model2 = _model()
    model2.load(path)
    for (n, p), (_, q) in zip(sorted(model.network.named_parameters()),
                              sorted(model2.network.named_parameters())):
        np.testing.assert_allclose(np.asarray(p._value), np.asarray(q._value),
                                   err_msg=n)


def test_model_checkpoint_callback(tmp_path):
    model = _model()
    save_dir = str(tmp_path / "ck")
    model.fit(XorDataset(32), batch_size=16, epochs=2, save_dir=save_dir,
              save_freq=1, verbose=0)
    assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
    assert os.path.exists(os.path.join(save_dir, "final.pdparams"))


def test_early_stopping():
    model = _model()
    es = EarlyStopping(monitor="acc", mode="max", patience=1, verbose=0,
                       baseline=1.1)  # impossible baseline -> stops fast
    model.fit(XorDataset(32), XorDataset(32, seed=1), batch_size=16,
              epochs=10, eval_freq=1, callbacks=[es], verbose=0)
    assert model.stop_training


def test_custom_callback_order():
    events = []

    class Rec(Callback):
        def on_epoch_begin(self, epoch, logs=None):
            events.append(("begin", epoch))

        def on_epoch_end(self, epoch, logs=None):
            events.append(("end", epoch))

    model = _model()
    model.fit(XorDataset(32), batch_size=16, epochs=2, callbacks=[Rec()],
              verbose=0)
    assert events == [("begin", 0), ("end", 0), ("begin", 1), ("end", 1)]


def test_lr_scheduler_callback_steps():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(2, 2))
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2,
                                          gamma=0.5)
    opt = paddle.optimizer.SGD(learning_rate=sched,
                               parameters=net.parameters())
    model = paddle.Model(net)
    model.prepare(opt, nn.CrossEntropyLoss())
    model.fit(XorDataset(32), batch_size=16, epochs=1, verbose=0)
    # 2 steps with step_size=2 -> one decay
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_visualdl_logs(tmp_path):
    model = _model()
    vdl = VisualDL(str(tmp_path / "vdl"))
    model.fit(XorDataset(32), batch_size=16, epochs=1, callbacks=[vdl],
              verbose=0)
    assert os.path.exists(str(tmp_path / "vdl" / "train.log"))


def test_summary(capsys):
    model = _model()
    info = model.summary()
    # (2*32 + 32) + (32*2 + 2)
    assert info["total_params"] == 96 + 66
    top = paddle.summary(model.network)
    assert top["trainable_params"] == info["total_params"]


def test_jit_compile_path():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(2, 32), nn.Tanh(), nn.Linear(32, 2))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), jit_compile=True)
    history = model.fit(XorDataset(), batch_size=32, epochs=4, verbose=0)
    assert history[-1]["loss"] < history[0]["loss"]
