"""BeamSearchDecoder / dynamic_decode / gather_tree (VERDICT r4 missing #6).

Oracle: an independent numpy beam search over the same GRU cell weights —
step-by-step expansion with explicit sorting, no shared code with the
jax implementation."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn


def _np_gru_step(params, x, h):
    wih, whh, bih, bhh = params
    hs = whh.shape[1]
    xg = x @ wih.T + bih
    hg = h @ whh.T + bhh

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    r = sig(xg[:, :hs] + hg[:, :hs])
    z = sig(xg[:, hs:2 * hs] + hg[:, hs:2 * hs])
    c = np.tanh(xg[:, 2 * hs:] + r * hg[:, 2 * hs:])
    return (1 - z) * c + z * h


def _np_beam_search(cell_params, emb, proj_w, proj_b, h0, start, end,
                    beam, steps):
    """Reference beam search for ONE batch row: returns (sequences, scores)
    sorted best-first, sequences padded with end after finish."""
    def log_softmax(v):
        v = v - v.max()
        return v - np.log(np.exp(v).sum())

    # beams: list of (tokens, logp, h, finished)
    beams = [([], 0.0, h0.copy(), False)]
    for _ in range(steps):
        cands = []
        for toks, lp, h, fin in beams:
            if fin:
                cands.append((toks + [end], lp, h, True))
                continue
            prev = toks[-1] if toks else start
            x = emb[prev][None, :]
            h2 = _np_gru_step(cell_params, x, h[None, :])[0]
            logits = h2 @ proj_w.T + proj_b
            lps = log_softmax(logits.astype(np.float64))
            for v in range(len(lps)):
                cands.append((toks + [v], lp + lps[v], h2,
                              v == end))
        cands.sort(key=lambda c: -c[1])
        beams = cands[:beam]
    return ([c[0] for c in beams], [c[1] for c in beams])


class TestBeamSearch:
    def _make(self, vocab=7, hidden=12, emb_dim=5, seed=0):
        rs = np.random.RandomState(seed)
        cell = nn.GRUCell(emb_dim, hidden)
        embedding = nn.Embedding(vocab, emb_dim)
        proj = nn.Linear(hidden, vocab)
        # randomize deterministic weights
        for p in list(cell.parameters()) + list(embedding.parameters()) \
                + list(proj.parameters()):
            p.set_value(rs.randn(*p.shape).astype(np.float32) * 0.7)
        return cell, embedding, proj, rs

    def test_matches_numpy_oracle(self):
        vocab, hidden, beam, steps = 7, 12, 3, 5
        cell, embedding, proj, rs = self._make(vocab, hidden)
        batch = 2
        h0 = rs.randn(batch, hidden).astype(np.float32)

        dec = nn.BeamSearchDecoder(
            cell, start_token=0, end_token=vocab - 1, beam_size=beam,
            embedding_fn=embedding, output_fn=proj)
        outs, final = nn.dynamic_decode(
            dec, inits=paddle.to_tensor(h0), max_step_num=steps)
        got_ids = np.asarray(outs._value)              # (batch, T, beam)
        got_scores = np.asarray(final.log_probs._value)

        cp = [np.asarray(p._value) for p in
              (cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh)]
        ew = np.asarray(embedding.weight._value)
        pw = np.asarray(proj.weight._value).T          # paddle Linear: x@W
        pb = np.asarray(proj.bias._value)
        for b in range(batch):
            seqs, scores = _np_beam_search(
                cp, ew, pw, pb, h0[b], 0, vocab - 1, beam, steps)
            for k in range(beam):
                np.testing.assert_array_equal(got_ids[b, :, k], seqs[k])
                np.testing.assert_allclose(got_scores[b, k], scores[k],
                                           rtol=2e-4)

    def test_beam1_equals_greedy(self):
        vocab, hidden = 9, 8
        cell, embedding, proj, rs = self._make(vocab, hidden, seed=4)
        h0 = rs.randn(1, hidden).astype(np.float32)
        dec = nn.BeamSearchDecoder(cell, 0, vocab - 1, 1,
                                   embedding_fn=embedding, output_fn=proj)
        outs, _ = nn.dynamic_decode(dec, inits=paddle.to_tensor(h0),
                                    max_step_num=6)
        got = np.asarray(outs._value)[0, :, 0]

        # stepwise greedy with the same layers
        h = paddle.to_tensor(h0)
        tok = paddle.to_tensor(np.asarray([0], np.int32))
        want = []
        for _ in range(6):
            out, h = cell(embedding(tok), h)
            tok = paddle.argmax(proj(out), axis=-1).astype("int32")
            want.append(int(np.asarray(tok._value)[0]))
            if want[-1] == vocab - 1:
                want += [vocab - 1] * (6 - len(want))
                break
        np.testing.assert_array_equal(got, want)

    def test_end_token_freezes_beam(self):
        """Once a beam emits end_token its score must stop changing and it
        must keep emitting end_token."""
        vocab, hidden, beam = 5, 6, 4
        cell, embedding, proj, rs = self._make(vocab, hidden, seed=7)
        # bias the projection hard toward end_token so beams finish early
        bias = np.zeros(vocab, np.float32)
        bias[vocab - 1] = 4.0
        proj.bias.set_value(bias)
        h0 = rs.randn(1, hidden).astype(np.float32)
        dec = nn.BeamSearchDecoder(cell, 0, vocab - 1, beam,
                                   embedding_fn=embedding, output_fn=proj)
        outs, final, lengths = nn.dynamic_decode(
            dec, inits=paddle.to_tensor(h0), max_step_num=8,
            return_length=True)
        ids = np.asarray(outs._value)[0]               # (T, beam)
        lens = np.asarray(lengths._value)[0]
        for k in range(beam):
            L = int(lens[k])
            assert L <= 8
            # after its length, a finished beam pads with end_token
            assert (ids[L:, k] == vocab - 1).all()

    def test_gather_tree_matches_manual(self):
        ids = np.asarray([[[2, 5], [3, 4]],
                          [[6, 7], [8, 9]],
                          [[1, 0], [2, 3]]], np.int32)     # (T=3, B=2, K=2)
        parents = np.asarray([[[0, 0], [0, 0]],
                              [[1, 0], [0, 1]],
                              [[0, 1], [1, 0]]], np.int32)
        got = np.asarray(
            paddle.nn.functional.gather_tree(
                paddle.to_tensor(ids), paddle.to_tensor(parents))._value)
        t, b, k = ids.shape
        want = np.zeros_like(ids)
        for bb in range(b):
            for kk in range(k):
                beam = kk
                for tt in range(t - 1, -1, -1):
                    want[tt, bb, kk] = ids[tt, bb, beam]
                    beam = parents[tt, bb, beam]
        np.testing.assert_array_equal(got, want)

    def test_under_jit(self):
        """The whole decode compiles as one program (scan-based)."""
        vocab, hidden = 6, 8
        cell, embedding, proj, rs = self._make(vocab, hidden, seed=2)
        h0 = rs.randn(2, hidden).astype(np.float32)
        dec = nn.BeamSearchDecoder(cell, 0, vocab - 1, 2,
                                   embedding_fn=embedding, output_fn=proj)

        def run(h):
            outs, _ = nn.dynamic_decode(dec, inits=paddle.to_tensor(h),
                                        max_step_num=4)
            return outs._value

        got = np.asarray(jax.jit(run)(jnp.asarray(h0)))
        want = np.asarray(run(jnp.asarray(h0)))
        np.testing.assert_array_equal(got, want)
