"""Fleet sensor plane (ISSUE 11): MetricHistory ring sampling, robust
anomaly detection (shared z-score + CUSUM, cooldown, byte-determinism),
SignalBus signals through serving, /varz on the DiagServer, history.json
in flight bundles, the zero-cost disarmed gate, and the bench-trajectory
sentinel."""

import json
import os
import subprocess
import sys
import tarfile
import tracemalloc
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from paddle_tpu.core.histogram import Histogram
from paddle_tpu.inference.decoding import (ContinuousBatchingEngine,
                                           GenerationConfig)
from paddle_tpu.models import llama as L
from paddle_tpu.observability import (AnomalyMonitor, CusumDetector,
                                      DiagServer, MetricHistory,
                                      RobustZScoreDetector, SignalBus,
                                      StragglerDetector, get_registry,
                                      robust_zscore)
from paddle_tpu.observability.anomaly import mad, median
from paddle_tpu.observability.flight import flight_recorder
from paddle_tpu.observability.timeseries import history_armed
from paddle_tpu.serving import SchedulerConfig, ServingScheduler

REPO = Path(__file__).resolve().parent.parent


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


@pytest.fixture()
def clean_plane():
    """Sensor-plane globals back to disarmed/unattached after each test."""
    yield
    history_armed[0] = False
    flight_recorder.disarm()
    flight_recorder.clear()
    flight_recorder._signals = None
    flight_recorder._dump_dir = None


def _setup_serving(max_new=4, num_slots=2, chunk=2, seed=3, clock=None,
                   **sched_kw):
    cfg = L.llama_tiny(num_hidden_layers=2)
    params = L.init_stacked_params(cfg, seed=seed)
    eng = ContinuousBatchingEngine(
        cfg, GenerationConfig(max_new_tokens=max_new, seed=seed),
        num_slots=num_slots, page_size=4, max_seq_len=32, chunk=chunk)
    kw = {}
    if clock is not None:
        kw = {"clock": clock, "sleep": lambda s: None}
    sched = ServingScheduler(eng, SchedulerConfig(**sched_kw), **kw)
    return params, eng, sched


# ---------------------------------------------------------------------------
# MetricHistory: windowed rates / slopes / quantiles on injected clocks
# ---------------------------------------------------------------------------

def test_history_counter_rate_and_gauge_slope():
    clk = FakeClock()
    h = MetricHistory(clock=clk, capacity=64, min_interval_s=1.0)
    ctr = [0.0]
    lvl = [2.0]
    h.track_counter("reqs", lambda: ctr[0])
    h.track_gauge("depth", lambda: lvl[0])
    for i in range(20):
        clk.advance(1.0)
        ctr[0] += 7.0               # 7 events/s
        lvl[0] = 2.0 + 0.5 * i      # +0.5/s
        assert h.sample()
    assert h.rate("reqs", 10.0) == pytest.approx(7.0)
    assert h.delta("reqs", 10.0) == pytest.approx(70.0)
    assert h.slope("depth", 10.0) == pytest.approx(0.5, rel=1e-6)
    assert h.latest("depth") == pytest.approx(2.0 + 0.5 * 19)


def test_history_windowed_quantile_from_bucket_deltas():
    clk = FakeClock()
    h = MetricHistory(clock=clk, capacity=64, min_interval_s=1.0)
    hist = Histogram(bounds=(1, 2, 5, 10, 20))
    h.track_histogram("lat", lambda: hist)
    # first window: all samples at ~4ms; later window: all at ~9ms —
    # a cumulative histogram would blend them, the windowed estimate
    # must see only the recent bucket deltas
    for i in range(30):
        clk.advance(1.0)
        hist.record(4.0 if i < 15 else 9.0)
        h.sample()
    q = h.window_quantile("lat", 0.5, 10.0)
    assert 5.0 <= q <= 10.0, q      # recent samples live in the (5,10] bucket
    assert h.window_mean("lat", 10.0) == pytest.approx(9.0)
    # full-history window blends both phases (deltas run from the first
    # RETAINED sample, so the very first observation is the baseline):
    # 14x4ms + 15x9ms over 29 observations
    assert h.window_mean("lat", None) == pytest.approx(191 / 29)


def test_history_ring_bounded_and_decimated():
    clk = FakeClock()
    h = MetricHistory(clock=clk, capacity=8, min_interval_s=1.0)
    h.track_gauge("g", lambda: 1.0)
    for _ in range(50):
        clk.advance(1.0)
        h.sample()
    assert len(h.series("g")) == 8          # ring bound
    clk.advance(0.25)
    assert not h.sample()                   # decimated: within interval
    assert h.snapshot_status()["series"]["g"] == 8
    snap = h.snapshot()
    assert set(snap) == {"g"} and len(snap["g"]["points"]) == 8


# ---------------------------------------------------------------------------
# shared robust z-score: the straggler detector delegates
# ---------------------------------------------------------------------------

def test_straggler_detector_delegates_to_shared_zscore():
    det = StragglerDetector(window=16, z_threshold=4.0, min_samples=8)
    vals = [0.1, 0.11, 0.1, 0.09, 0.1, 0.12, 0.1, 0.11, 0.1]
    for v in vals:
        det.observe(v, source="delegate_test")
    # identical math through either entry point
    assert det.zscore(0.5) == robust_zscore(0.5, det._samples,
                                            det.min_samples)
    # warmup semantics preserved: below min_samples -> 0
    assert robust_zscore(9.9, [1.0, 1.0], min_samples=8) == 0.0
    # MAD-of-zero fallback preserved (uniform window still scores)
    z = robust_zscore(0.2, [0.1] * 10)
    assert z == pytest.approx((0.2 - 0.1) / (0.1 * 0.05))


def test_median_mad_primitives():
    assert median([3.0, 1.0, 2.0]) == 2.0
    assert median([4.0, 1.0, 2.0, 3.0]) == 2.5
    assert mad([1.0, 2.0, 3.0, 4.0, 100.0]) == 1.0   # robust to the spike


# ---------------------------------------------------------------------------
# anomaly detection: level shift, slow drift, cooldown, determinism
# ---------------------------------------------------------------------------

def _level_series():
    # quiet baseline with mild deterministic jitter, then a 5x shift
    return [1.0 + 0.01 * (i % 3) for i in range(40)] + [5.0] * 20


def _drift_series():
    # per-sample increment far below the jitter, but accumulating: the
    # windowed z-score absorbs it, CUSUM must not
    out = []
    for i in range(120):
        base = 1.0 + 0.02 * ((i * 7) % 5)           # deterministic noise
        drift = 0.01 * max(0, i - 40)               # slow ramp after 40
        out.append(base + drift)
    return out


def test_level_shift_fires_exactly_once_with_cooldown(clean_plane):
    mon = AnomalyMonitor(
        cooldown_s=1000.0,
        detector_factory=lambda: [RobustZScoreDetector(
            window=32, z_threshold=6.0, min_samples=8)])
    fired = []
    for i, v in enumerate(_level_series()):
        fired += mon.observe("itl_ms", v, float(i))
    assert len(fired) == 1, fired
    assert fired[0]["series"] == "itl_ms"
    assert fired[0]["detector"] == "zscore"
    assert fired[0]["direction"] == "up"
    assert fired[0]["t"] == 40.0                    # the shift sample
    snap = mon.snapshot()["itl_ms"]
    assert snap["fired"] == 1
    assert snap["suppressed"] > 0                   # sustained shift held


def test_slow_drift_cusum_fires_once(clean_plane):
    mon = AnomalyMonitor(
        cooldown_s=1000.0,
        detector_factory=lambda: [CusumDetector(k=0.5, h=8.0,
                                                baseline=24)])
    zmon = AnomalyMonitor(
        cooldown_s=1000.0,
        detector_factory=lambda: [RobustZScoreDetector(
            window=16, z_threshold=8.0, min_samples=8)])
    fired, zfired = [], []
    for i, v in enumerate(_drift_series()):
        fired += mon.observe("burn", v, float(i))
        zfired += zmon.observe("burn", v, float(i))
    assert len(fired) == 1, fired
    assert fired[0]["detector"] == "cusum"
    assert fired[0]["direction"] == "up"
    assert fired[0]["t"] > 40.0                     # after the ramp starts
    # a SHORT-window z-score misses the drift entirely (each sample is
    # ordinary against its drifting window) — that's why CUSUM exists
    assert zfired == []


def test_anomaly_cooldown_expiry_pages_again(clean_plane):
    mon = AnomalyMonitor(cooldown_s=10.0, detector_factory=lambda: [
        RobustZScoreDetector(window=32, z_threshold=6.0, min_samples=8)])
    series = _level_series()
    fired = []
    for i, v in enumerate(series):
        fired += mon.observe("x", v, float(i))
    assert len(fired) == 2                          # 40, then 50 (cooldown)
    assert fired[1]["t"] == 50.0


def test_idle_zero_series_first_activity_scores_sanely(clean_plane):
    """A series idling at exactly 0 (queue depth, parked count) has no
    scale information — the MAD fallback would otherwise degenerate to
    ~1e-12 and score the first real sample at z~1e11, paging on every
    idle->active transition. The z-score detector must treat first
    activity as a level START (no fire); CUSUM may legitimately note
    the 0->busy regime change, but only with a sane standardized score,
    never the degenerate-scale explosion."""
    mon = AnomalyMonitor(cooldown_s=1000.0)
    fired = []
    for i in range(60):
        fired += mon.observe("queue_depth", 0.0, float(i))
    assert fired == []                       # idle never pages
    for i in range(60, 120):
        fired += mon.observe("queue_depth", 3.0 + 0.1 * (i % 4),
                             float(i))
    assert all(f["detector"] != "zscore" for f in fired), fired
    assert all(abs(f["score"]) < 1e3 for f in fired), fired
    # a REAL shift on the established busy baseline still pages
    mon2 = AnomalyMonitor(cooldown_s=1000.0)
    for i in range(60):
        mon2.observe("busy", 3.0 + 0.1 * (i % 4), float(i))
    later = []
    for i in range(60, 80):
        later += mon2.observe("busy", 30.0, float(i))
    assert len(later) >= 1


def test_spec_acceptance_reader_uses_snapshot_ratio(clean_plane):
    from paddle_tpu.observability.signals import _spec_acceptance

    class _Spec:
        def snapshot(self):
            return {"acceptance_ratio": 0.42, "drafted": 100}

    class _Eng:
        spec = _Spec()

    assert _spec_acceptance(_Eng()) == pytest.approx(0.42)
    assert _spec_acceptance(object()) == 1.0      # no speculation


def test_anomaly_detection_byte_deterministic(clean_plane):
    def run():
        mon = AnomalyMonitor(cooldown_s=25.0)
        out = []
        for i, v in enumerate(_level_series() + _drift_series()):
            out += mon.observe("s", v, float(i) * 0.5)
        return json.dumps(out, sort_keys=True)
    assert run() == run()


def test_anomaly_metrics_registered(clean_plane):
    mon = AnomalyMonitor(cooldown_s=1000.0)
    for i, v in enumerate(_level_series()):
        mon.observe("det_series", v, float(i))
    reg = get_registry()
    c = reg.get("paddle_anomaly_events_total")
    assert c is not None
    total = sum(v for k, v in c.snapshot().items()
                if "det_series" in k)
    assert total >= 1
    g = reg.get("paddle_anomaly_score")
    assert g is not None and g.value(series="det_series") >= 0.0


# ---------------------------------------------------------------------------
# SignalBus through serving + /varz + flight bundle
# ---------------------------------------------------------------------------

def test_signal_bus_serving_e2e(clean_plane):
    clk = FakeClock()
    params, eng, sched = _setup_serving(clock=clk, max_queue_depth=8)
    bus = sched.attach_signal_bus(interval_s=1.0, window_s=60.0)
    assert sched.signal_bus is bus
    bus.arm()
    assert history_armed[0]
    for i in range(6):
        sched.submit(np.array([2, 3, 4, 5], np.int32), priority=i % 2)
    while sched.pending:
        clk.advance(1.5)            # every step crosses the bus interval
        sched.step(params)
    v = bus.values()
    for name in ("queue_depth", "page_pressure", "slo_burn",
                 "spec_acceptance", "queue_wait_share"):
        assert name in v, sorted(v)
    assert v["queue_depth"]["value"] is not None
    assert 0.0 <= v["page_pressure"]["raw"] <= 1.0
    assert bus.ticks >= 3
    # the history tracked the sink's histograms + counters too
    assert bus.history.latest("tokens_total") > 0
    # statusz carries the signal summary
    assert "signals" in sched.statusz()
    doc = bus.varz()
    assert doc["armed"] and "anomalies" in doc and "history" in doc
    bus.disarm()
    assert not history_armed[0]


def test_signal_bus_disarmed_never_ticks(clean_plane):
    clk = FakeClock()
    params, eng, sched = _setup_serving(clock=clk)
    bus = sched.attach_signal_bus(interval_s=0.0)
    assert not history_armed[0]     # attach does NOT arm
    sched.submit(np.array([2, 3, 4], np.int32))
    while sched.pending:
        clk.advance(1.0)
        sched.step(params)
    assert bus.ticks == 0


def test_varz_endpoint_e2e(clean_plane):
    clk = FakeClock()
    bus = SignalBus(clock=clk, interval_s=1.0)
    depth = [3.0]
    bus.signal("queue_depth", lambda: depth[0])
    bus.arm()
    for i in range(10):
        clk.advance(1.0)
        depth[0] = 3.0 + i
        bus.tick()
    srv = DiagServer(port=0)
    srv.attach_signals(bus)
    port = srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/varz", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["signals"]["queue_depth"]["value"] is not None
        assert doc["signals"]["queue_depth"]["trend_per_s"] > 0
        assert doc["armed"] is True
        # /varz listed on the index; signals section joins /statusz
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10) as r:
            assert "/varz" in json.loads(r.read())["endpoints"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=10) as r:
            assert "signals" in json.loads(r.read())
    finally:
        srv.stop()
        bus.disarm()


def test_varz_404_without_bus(clean_plane):
    srv = DiagServer(port=0)
    port = srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/varz",
                                   timeout=10)
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_flight_bundle_embeds_history_json(tmp_path, clean_plane):
    clk = FakeClock()
    bus = SignalBus(clock=clk, interval_s=1.0, capacity=128)
    val = [1.0]
    bus.signal("sig", lambda: val[0])
    bus.arm()
    for i, v in enumerate(_level_series()):
        clk.advance(1.0)
        val[0] = v
        bus.tick()
    flight_recorder.arm(capacity=64, dump_dir=str(tmp_path))
    path = flight_recorder.dump_debug_bundle(
        str(tmp_path / "bundle.tar.gz"), reason="test")
    assert os.path.getsize(path) < 256 * 1024       # bounded bundle
    with tarfile.open(path) as tar:
        names = tar.getnames()
        assert "history.json" in names
        doc = json.loads(tar.extractfile("history.json").read())
    assert doc["schema_version"] == 1
    assert doc["kind"] == "paddle_tpu.history"
    assert "sig" in doc["series"]
    assert len(doc["series"]["sig"]["points"]) <= 128
    assert doc["signals"]["sig"]["value"] is not None
    # the level shift the bus watched landed in the bundle's anomalies
    assert any(a["series"] == "sig" for a in doc["anomalies"])


def test_history_gate_disarmed_inert(clean_plane):
    """The disarmed per-step cost is one list index — allocation-free,
    same contract (and same tracemalloc harness) as the flight/timeline
    gates in bench_obs_overhead."""
    assert not history_armed[0]
    tracemalloc.start()
    before = tracemalloc.get_traced_memory()[0]
    for _ in range(20_000):
        pass
    baseline = tracemalloc.get_traced_memory()[0] - before
    before = tracemalloc.get_traced_memory()[0]
    for _ in range(20_000):
        _ = history_armed[0]
    after = tracemalloc.get_traced_memory()[0]
    tracemalloc.stop()
    assert max(0, after - before - baseline) < 2048


# ---------------------------------------------------------------------------
# bench sentinel: trajectory replay passes, synthetic regression fails
# ---------------------------------------------------------------------------

def _run_sentinel(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "bench_sentinel.py"),
         *args],
        capture_output=True, text=True, cwd=str(REPO))


@pytest.mark.skipif(not list(REPO.glob("BENCH_r*.json")),
                    reason="no checked-in trajectory")
def test_sentinel_replay_of_checked_in_trajectory_passes():
    r = _run_sentinel("--replay")
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["pass"] and doc["entries"] >= 2


@pytest.mark.skipif(not list(REPO.glob("BENCH_r*.json")),
                    reason="no checked-in trajectory")
def test_sentinel_flags_synthetic_itl_regression(tmp_path):
    # newest entry that is actually judgeable against default history:
    # carries tokens_per_sec AND has >= 2 same-(metric, unit) peers
    # (the fusion_ab series seeded in round 18 starts with one entry,
    # so the bare newest file would exit 3 on no_comparable_history)
    paths = sorted(REPO.glob("BENCH_r*.json"))
    parsed = [json.loads(p.read_text())["parsed"] for p in paths]
    groups: dict = {}
    for e in parsed:
        key = (e.get("metric"), e.get("unit"))
        groups[key] = groups.get(key, 0) + 1
    judgeable = [p for p, e in zip(paths, parsed)
                 if "tokens_per_sec" in e
                 and groups[(e.get("metric"), e.get("unit"))] >= 3]
    if not judgeable:
        pytest.skip("no BENCH entry with tokens_per_sec and >=2 "
                    "same-(metric,unit) peers in the trajectory")
    newest = judgeable[-1]
    entry = json.loads(newest.read_text())["parsed"]
    entry["tokens_per_sec"] /= 2.0          # 2x ITL == half throughput
    bad = tmp_path / "regressed.json"
    bad.write_text(json.dumps(entry))
    r = _run_sentinel("--fresh", str(bad))
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert not doc["pass"]
    assert any(row["field"] == "tokens_per_sec"
               for row in doc["regressions"])
    # the unmodified line sails through
    good = tmp_path / "fresh.json"
    good.write_text(json.dumps(
        json.loads(newest.read_text())["parsed"]))
    r = _run_sentinel("--fresh", str(good))
    assert r.returncode == 0, r.stdout + r.stderr


def test_sentinel_bands_are_mad_based(tmp_path):
    """Unit-level: a fresh value inside median±max(k·1.4826·MAD,
    floor·median) passes, outside fails; direction respects the unit."""
    traj = []
    for i, tps in enumerate((1000.0, 1010.0, 990.0, 1005.0)):
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps({"parsed": {
            "metric": "m", "unit": "MFU", "value": 0.5,
            "tokens_per_sec": tps}}))
        traj.append(p)
    glob_arg = str(tmp_path / "BENCH_r*.json")
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps({"metric": "m", "unit": "MFU",
                              "value": 0.5, "tokens_per_sec": 980.0}))
    r = _run_sentinel("--fresh", str(ok), "--trajectory", glob_arg)
    assert r.returncode == 0, r.stdout
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"metric": "m", "unit": "MFU",
                               "value": 0.5, "tokens_per_sec": 700.0}))
    r = _run_sentinel("--fresh", str(bad), "--trajectory", glob_arg)
    assert r.returncode == 1, r.stdout


def test_sentinel_renamed_metric_fails_loudly_not_vacuously(tmp_path):
    """A fresh line whose (metric, unit) has no trajectory peers must
    NOT report clean — exit 3 + no_comparable_history (a regression on
    a renamed workload would otherwise pass silently); opt out with
    --allow-new-metric."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({"parsed": {
        "metric": "old_name", "unit": "MFU", "value": 0.5,
        "tokens_per_sec": 1000.0}}))
    glob_arg = str(tmp_path / "BENCH_r*.json")
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps({"metric": "NEW_name", "unit": "MFU",
                                 "value": 0.5,
                                 "tokens_per_sec": 500.0}))
    r = _run_sentinel("--fresh", str(fresh), "--trajectory", glob_arg)
    assert r.returncode == 3, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["verdict"] == "no_comparable_history" and not doc["pass"]
    r = _run_sentinel("--fresh", str(fresh), "--trajectory", glob_arg,
                      "--allow-new-metric")
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_headers_carry_schema_version():
    sys.path.insert(0, str(REPO / "benchmarks"))
    try:
        from _telemetry import BENCH_SCHEMA_VERSION, run_header
    finally:
        sys.path.pop(0)
    h = run_header("unit")
    assert h["schema_version"] == BENCH_SCHEMA_VERSION >= 2
    assert h["bench"] == "unit"
    assert "python" in h and "jax_platforms" in h
