"""Automatic mixed precision.

Parity with python/paddle/amp/{auto_cast,grad_scaler}.py of the reference
(SURVEY.md §2.5 AMP row). TPU-first: bf16 is the native half type, needs no
loss scaling; ``GradScaler`` keeps the full dynamic-loss-scale state machine
for fp16 parity and becomes a transparent passthrough for bf16/disabled.

O1: ops on an allow-list compute in low precision (inputs cast at dispatch).
O2: ``decorate`` casts model params to low precision and (via optimizer
``multi_precision``) keeps fp32 master weights — the main_grad idiom.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor
from ..core import dispatch as _dispatch

# ops that benefit from low precision (matmul-class); parity with the
# reference's white list (paddle/fluid/imperative/amp_auto_cast.cc)
WHITE_LIST = {"matmul", "mm", "bmm", "linear", "conv2d", "conv1d", "conv3d",
              "einsum", "flash_attention", "attention_masked"}
# ops kept in fp32 (reductions/normalizations/losses)
BLACK_LIST = {"softmax", "log_softmax", "cross_entropy", "layer_norm",
              "batch_norm", "rms_norm", "mean", "sum", "norm", "logsumexp",
              "exp", "log", "cosine_similarity"}

_amp_state = {"enabled": False, "dtype": jnp.bfloat16, "level": "O1",
              "custom_white": set(), "custom_black": set()}


def amp_state():
    return _amp_state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = dict(_amp_state)
    _amp_state.update(
        enabled=enable, dtype=convert_dtype(dtype), level=level,
        custom_white=set(custom_white_list or ()),
        custom_black=set(custom_black_list or ()))
    try:
        yield
    finally:
        _amp_state.update(prev)


amp_guard = auto_cast


def _target_dtype(op_name, cur_dtype):
    """Return the dtype an input should be cast to under the active amp mode,
    or None for no cast."""
    level = _amp_state["level"]
    dt = _amp_state["dtype"]
    white = (WHITE_LIST | _amp_state["custom_white"]) - _amp_state["custom_black"]
    black = BLACK_LIST | _amp_state["custom_black"]
    if level == "O2":
        if op_name in black and cur_dtype in (jnp.bfloat16, jnp.float16):
            return jnp.float32
        return None
    if op_name in white and cur_dtype == jnp.float32:
        return dt
    return None


# hook into the dispatcher (dispatch.apply consults amp_cast_hook per call)
def _amp_hook(op_name, args):
    if not _amp_state["enabled"]:
        return args
    cast_args = []
    for a in args:
        if isinstance(a, Tensor):
            tgt = _target_dtype(op_name, a._value.dtype)
            if tgt is not None:
                # real recorded cast op so the tape transposes dtypes correctly
                a = a.astype(tgt)
        cast_args.append(a)
    return tuple(cast_args)


_dispatch.amp_cast_hook = _amp_hook


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Parity with paddle.amp.decorate: cast model to low precision (O2) and
    turn on optimizer master weights."""
    d = convert_dtype(dtype)
    single_model = not isinstance(models, (list, tuple))
    single_opt = not isinstance(optimizers, (list, tuple))
    model_list = [models] if single_model else list(models)
    opt_list = [optimizers] if single_opt or optimizers is None else list(optimizers)
    if level == "O2":
        for m in model_list:
            if m is not None:
                m.to(dtype=d)
        for o in opt_list:
            if o is not None:
                o._multi_precision = True
    if optimizers is None:
        return models if single_model else model_list
    return (models if single_model else model_list,
            optimizers if single_opt else opt_list)


class GradScaler:
    """Dynamic loss scaling, parity with paddle.amp.GradScaler.

    On TPU with bf16 this is effectively identity (enable=False default when
    dtype is bf16), but the fp16 state machine is implemented faithfully:
    scale *= incr_ratio every incr_every_n_steps good steps; on inf/nan skip
    the step and scale *= decr_ratio.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import jax.numpy as jnp
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad_value is None:
                continue
            g = p._grad_value.astype(jnp.float32) * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found = True
            p._grad_value = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._found_inf:
            self.unscale_(optimizer)
        if self._found_inf:
            self._update_on_inf()
            self._found_inf = False
            return
        optimizer.step()
        self._update_on_good()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        pass

    def _update_on_good(self):
        if not self._dynamic:
            return
        self._good += 1
        self._bad = 0
        if self._good >= self._incr_every:
            self._scale *= self._incr_ratio
            self._good = 0

    def _update_on_inf(self):
        if not self._dynamic:
            return
        self._bad += 1
        self._good = 0
        if self._bad >= self._decr_every:
            self._scale = max(self._scale * self._decr_ratio, 1.0)
            self._bad = 0

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good": self._good, "bad": self._bad}

    def load_state_dict(self, s):
        self._scale = s["scale"]
        self._good = s["good"]
        self._bad = s["bad"]


from . import debugging  # noqa: E402,F401
