"""Numerics debugging: nan/inf detection in eager AND compiled code.

Rebuild of paddle.amp.debugging + FLAGS_check_nan_inf
(paddle/fluid/framework/details/nan_inf_utils_detail.{cc,cu}:§0,
python/paddle/amp/debugging.py:§0 — SURVEY.md §5.2). The reference scans
every op output on device with a CUDA kernel; the TPU-native equivalents:

* eager: ``check_numerics`` / the dispatch-level hook armed by
  ``FLAGS_check_nan_inf`` (core/dispatch.py) — host-side scans.
* compiled: ``checkify_wrap`` functionalizes a jitted function with
  ``jax.experimental.checkify`` float checks, so nan/inf *inside* an XLA
  program is caught with the generating primitive named — the
  checkify/debug_callback pass SURVEY §5.2 calls for.
"""

from __future__ import annotations

import functools
from enum import Enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..flags import set_flags, flag_value


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    """Parity with paddle.amp.debugging.TensorCheckerConfig."""

    def __init__(self, enable: bool = True,
                 debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir: Optional[str] = None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list


def enable_tensor_checker(checker_config: TensorCheckerConfig) -> None:
    """Arm the dispatch-level nan/inf scan (FLAGS_check_nan_inf), honouring
    debug_mode (abort vs report-only) and the op include/skip lists."""
    from ..core import dispatch as _d
    set_flags({"check_nan_inf": bool(checker_config.enable)})
    _d.nan_inf_abort[0] = (checker_config.debug_mode
                           == DebugMode.CHECK_NAN_INF_AND_ABORT)
    _d.nan_inf_skip_ops = set(checker_config.skipped_op_list or ())
    _d.nan_inf_check_ops = set(checker_config.checked_op_list or ())


def disable_tensor_checker() -> None:
    from ..core import dispatch as _d
    set_flags({"check_nan_inf": False})
    _d.nan_inf_abort[0] = True
    _d.nan_inf_skip_ops = set()
    _d.nan_inf_check_ops = set()


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Eager scan; raises FloatingPointError on nan/inf (abort mode) or
    returns (num_nan, num_inf) counts."""
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if not jnp.issubdtype(v.dtype, jnp.floating):
        return 0, 0
    n_nan = int(jnp.sum(jnp.isnan(v)))
    n_inf = int(jnp.sum(jnp.isinf(v)))
    if (n_nan or n_inf) and debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise FloatingPointError(
            f"nan/inf in {op_type or 'tensor'} {var_name}: "
            f"{n_nan} nan, {n_inf} inf (shape {tuple(v.shape)})")
    return n_nan, n_inf


def checkify_wrap(fn: Callable, *, jit: bool = True) -> Callable:
    """Wrap a (jittable) array function so nan/inf produced INSIDE the
    compiled program raises FloatingPointError naming the primitive.

    This is how ``FLAGS_check_nan_inf`` extends into jit-world: the host
    scan in dispatch can't see intermediate values of a fused XLA program,
    checkify can. Cost: checks compile into the program — debug builds
    only, like the reference's flag.
    """
    from jax.experimental import checkify

    target = jax.jit(fn) if jit else fn
    checked = checkify.checkify(target, errors=checkify.float_checks)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        err, out = checked(*args, **kwargs)
        msg = err.get()
        if msg is not None:
            raise FloatingPointError(f"nan/inf inside compiled fn: {msg}")
        return out

    return wrapper


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    raise NotImplementedError(
        "tensor-dump comparison is a GPU-workflow tool; on TPU use "
        "checkify_wrap plus jax.debug.print for in-program inspection")
