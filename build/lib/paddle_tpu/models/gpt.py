"""GPT model family on the FusedMultiTransformer path.

This is workload #3's surface (SURVEY.md §2.2 fused_multi_transformer row:
"used by ERNIE/GPT inference+pretraining encoder path"): a GPT-style
causal LM whose decoder stack is ONE fused op — the incubate
FusedMultiTransformer layer backed by the scanned/fused block in
ops/fused_transformer_block.py (Pallas flash attention inside) — rather
than a per-layer Python loop. KV-cache generation rides the same op's
decode mode (reference: fused_multi_transformer CUDA decode with CacheKV).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..incubate.nn.layer.fused_transformer import FusedMultiTransformer
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from .. import creation


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    activation: str = "gelu"


def gpt_tiny(**over) -> GPTConfig:
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=64)
    base.update(over)
    return GPTConfig(**base)


class GPTEmbeddings(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = self.create_parameter(
            (config.vocab_size, config.hidden_size),
            default_initializer=I.Normal(0.0, 0.02))
        self.position_embeddings = self.create_parameter(
            (config.max_position_embeddings, config.hidden_size),
            default_initializer=I.Normal(0.0, 0.02))

    def forward(self, input_ids, position_offset: int = 0):
        from ..core.dispatch import apply

        def fn(ids, we, pe):
            s = ids.shape[-1]
            tok = jnp.take(we, ids.astype(jnp.int32), axis=0)
            pos = jax.lax.dynamic_slice_in_dim(pe, position_offset, s, 0)
            return tok + pos[None]

        return apply(fn, input_ids, self.word_embeddings,
                     self.position_embeddings, op_name="gpt_embeddings")


class GPTModel(Layer):
    """Embeddings → fused decoder stack → final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.decoder = FusedMultiTransformer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, activation=config.activation,
            normalize_before=True, epsilon=config.layer_norm_epsilon,
            num_layers=config.num_hidden_layers)
        from ..nn.common_layers import LayerNorm
        self.final_layernorm = LayerNorm(config.hidden_size,
                                         epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, caches=None, time_step: Optional[int] = None,
                gen_cache_len: Optional[int] = None):
        x = self.embeddings(input_ids,
                            position_offset=time_step if time_step else 0)
        out = self.decoder(x, caches=caches, time_step=time_step,
                           gen_cache_len=gen_cache_len)
        if isinstance(out, tuple):
            h, kv = out
            return self.final_layernorm(h), kv
        return self.final_layernorm(out)


class GPTForCausalLM(Layer):
    """LM head tied to the word embedding (reference GPT pretrain head)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)

    def forward(self, input_ids, caches=None, time_step=None,
                gen_cache_len=None):
        out = self.gpt(input_ids, caches=caches, time_step=time_step,
                       gen_cache_len=gen_cache_len)
        kv = None
        if isinstance(out, tuple):
            out, kv = out
        from ..core import math_ops as M
        logits = M.matmul(out, self.gpt.embeddings.word_embeddings,
                          transpose_y=True)
        return (logits, kv) if kv is not None else logits

    def compute_loss(self, input_ids, labels):
        logits = self(input_ids)
        return F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]), ignore_index=-100)

    # -- generation over the fused decode path ------------------------------

    def generate(self, input_ids, max_new_tokens: int = 16,
                 eos_token_id: Optional[int] = None):
        """Greedy KV-cache generation (host loop over the fused decode op;
        the bucketed compiled loop for serving lives in
        paddle_tpu.inference.decoding)."""
        from ..core import autograd as _ag
        ids = input_ids if isinstance(input_ids, Tensor) else \
            creation.to_tensor(np.asarray(input_ids))
        b, t = ids.shape
        cache_len = t + max_new_tokens
        if cache_len > self.config.max_position_embeddings:
            raise ValueError("generation exceeds max_position_embeddings")
        with _ag.no_grad():
            logits, kv = self(ids, gen_cache_len=cache_len)
            toks = [np.asarray(jnp.argmax(
                logits._value[:, -1].astype(jnp.float32), -1))]
            for i in range(max_new_tokens - 1):
                step_ids = creation.to_tensor(toks[-1][:, None].astype(np.int32))
                logits, kv = self(step_ids, caches=kv, time_step=t + i)
                toks.append(np.asarray(jnp.argmax(
                    logits._value[:, 0].astype(jnp.float32), -1)))
        out = np.stack(toks, axis=1).astype(np.int32)
        if eos_token_id is not None:
            # right-truncate after first EOS per row (parity convenience)
            for r in range(out.shape[0]):
                hit = np.where(out[r] == eos_token_id)[0]
                if hit.size:
                    out[r, hit[0] + 1:] = eos_token_id
        return out
