"""GPT-MoE: decoder blocks whose FFN is an expert-parallel MoELayer.

Workload #4 of BASELINE.md ("GPT-MoE with Fleet expert parallel"). Reference
surface: the PaddleNLP GPT-MoE recipe over
python/paddle/incubate/distributed/models/moe/MoELayer with
global_scatter/global_gather dispatch (SURVEY.md §2.4 EP row). TPU-native:
experts shard over a mesh axis (``moe_group``); MoELayer routes tokens
through ops.moe_ops.expert_parallel_apply — an explicit ``lax.all_to_all``
over ICI — when the group spans devices, and the dense einsum path otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, LayerList, Sequential
from ..nn.common_layers import LayerNorm, Linear
from ..core.tensor import Tensor
from ..incubate.distributed.models.moe import MoELayer
from .gpt import GPTConfig, GPTEmbeddings


@dataclass
class GPTMoEConfig(GPTConfig):
    num_experts: int = 8
    moe_topk: int = 2
    moe_gate: str = "gshard"
    capacity_factor: tuple = (1.2, 2.4)
    aux_loss_coef: float = 0.01
    # decoder layers using MoE FFN (every layer by default)
    moe_layer_interval: int = 1


def gpt_moe_tiny(**over) -> GPTMoEConfig:
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=64, num_experts=8, moe_topk=2)
    base.update(over)
    return GPTMoEConfig(**base)


class GPTSelfAttention(Layer):
    def __init__(self, config: GPTMoEConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.qkv = Linear(h, 3 * h)
        self.out_proj = Linear(h, h)

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, h // self.num_heads])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.out_proj(out.reshape([b, s, h]))


def _expert_ffn(config: GPTMoEConfig) -> Layer:
    from ..nn.common_layers import GELU, ReLU

    act = {"gelu": GELU, "relu": ReLU}[config.activation]
    return Sequential(
        Linear(config.hidden_size, config.intermediate_size),
        act(),
        Linear(config.intermediate_size, config.hidden_size))


class GPTMoEBlock(Layer):
    def __init__(self, config: GPTMoEConfig, use_moe: bool, moe_group=None):
        super().__init__()
        eps = config.layer_norm_epsilon
        self.ln1 = LayerNorm(config.hidden_size, epsilon=eps)
        self.attn = GPTSelfAttention(config)
        self.ln2 = LayerNorm(config.hidden_size, epsilon=eps)
        self.use_moe = use_moe
        if use_moe:
            self.mlp = MoELayer(
                config.hidden_size,
                experts=[_expert_ffn(config)
                         for _ in range(config.num_experts)],
                gate=config.moe_gate, topk=config.moe_topk,
                capacity_factor=config.capacity_factor,
                moe_group=moe_group)
        else:
            self.mlp = _expert_ffn(config)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class GPTMoEForCausalLM(Layer):
    """GPT with MoE FFN layers; LM head tied to the word embedding.

    ``moe_group``: a paddle_tpu.distributed Group naming the mesh axis the
    experts shard over (the Fleet expert-parallel group); None = dense.
    """

    def __init__(self, config: GPTMoEConfig, moe_group=None):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.blocks = LayerList([
            GPTMoEBlock(config,
                        use_moe=(i % config.moe_layer_interval == 0),
                        moe_group=moe_group)
            for i in range(config.num_hidden_layers)])
        self.final_layernorm = LayerNorm(config.hidden_size,
                                         epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids):
        x = self.embeddings(input_ids)
        for blk in self.blocks:
            x = blk(x)
        return self.final_layernorm(x)

    def logits(self, input_ids):
        from ..core import math_ops as M
        h = self(input_ids)
        return M.matmul(h, self.embeddings.word_embeddings, transpose_y=True)

    def aux_loss(self):
        total = None
        for blk in self.blocks:
            la = getattr(blk.mlp, "l_aux", None)
            if la is not None:
                total = la if total is None else total + la
        return total

    def compute_loss(self, input_ids, labels):
        logits = self.logits(input_ids)
        loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]), ignore_index=-100)
        aux = self.aux_loss()
        if aux is not None and self.config.aux_loss_coef:
            loss = loss + self.config.aux_loss_coef * aux
        return loss
