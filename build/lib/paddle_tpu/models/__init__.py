"""Model zoo: flagship LLM families (vision zoo lives in paddle_tpu.vision).

llama — TP/PP/DP/SP hybrid training flagship (workload #2).
gpt   — FusedMultiTransformer pretraining/inference path (workload #3).
ernie — bidirectional encoder on fused attention/FFN (workload #3).
"""

from . import llama  # noqa: F401
from . import gpt  # noqa: F401
from . import ernie  # noqa: F401
