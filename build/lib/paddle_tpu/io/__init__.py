"""``paddle_tpu.io`` — datasets and DataLoader.

Parity with python/paddle/io/ of the reference (dataloader_iter, worker,
batch_sampler — SURVEY.md §2.5 DataLoader row). TPU-first: the loader is a
host-side component; multiprocess workers feed numpy batches which the train
step moves to device (or `jax.make_array_from_process_local_data` under
multi-host data parallelism — see distributed.io).
"""

from __future__ import annotations

import itertools
import math
import os
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no static length")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        self.tensors = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                        for t in tensors]

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    idx = np.random.permutation(len(dataset))
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, idx[off:off + ln].tolist()))
        off += ln
    return out


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    """Epoch-deterministic shuffling: the permutation is a pure function of
    (seed, epoch), so a mid-epoch resume (TrainState.skip_batches after
    set_epoch) replays exactly the already-consumed prefix."""

    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None, seed: int = 0):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(self.seed + self.epoch)
        if self.replacement:
            return iter(rng.randint(0, n, size=self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), size=self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks.

    Parity with python/paddle/io/dataloader/batch_sampler.py::
    DistributedBatchSampler (SURVEY.md §2.5). On TPU the "rank" is the
    data-parallel process index.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as _env
            num_replicas = num_replicas if num_replicas is not None else _env.get_world_size()
            rank = rank if rank is not None else _env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


# ---------------------------------------------------------------------------
# collate
# ---------------------------------------------------------------------------
def numpy_collate_fn(batch):
    """Collate to host numpy (no device work) — what worker processes run:
    device placement must happen in the trainer process, never in a worker
    (a worker touching jax would initialize its own backend — on TPU, dial
    the chip — per process)."""
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return [numpy_collate_fn([b[i] for b in batch])
                for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: numpy_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _wrap_collated(tree):
    if isinstance(tree, np.ndarray):
        return Tensor(tree)
    if isinstance(tree, list):
        return [_wrap_collated(e) for e in tree]
    if isinstance(tree, dict):
        return {k: _wrap_collated(v) for k, v in tree.items()}
    return tree


def default_collate_fn(batch):
    # single recursion shared with the multiprocess path: workers run
    # numpy_collate_fn, the trainer side wraps — serial mode composes the
    # same two steps so the two paths cannot drift
    return _wrap_collated(numpy_collate_fn(batch))


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------
class WorkerInfo:
    """Parity with paddle.io.get_worker_info()."""

    def __init__(self, id: int, num_workers: int, dataset=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = [None]


def get_worker_info():
    return _worker_info[0]


def _worker_loop(dataset, collate_fn, idx_queue, out_queue, init_fn,
                 worker_id: int, num_workers: int):
    """Worker process body (reference: dataloader/worker.py _worker_loop).
    Must be module-level so spawn contexts can pickle it."""
    # Safety net: if user code in this worker does touch jax, keep it on the
    # CPU backend — a worker must never dial the accelerator (the axon
    # sitecustomize would otherwise pick the TPU platform and block).
    try:
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    _worker_info[0] = WorkerInfo(worker_id, num_workers, dataset)
    if init_fn is not None:
        init_fn(worker_id)
    try:
        while True:
            item = idx_queue.get()
            if item is None:
                break
            b, idxs = item
            batch = collate_fn([dataset[i] for i in idxs])
            out_queue.put(("ok", (b, batch)))
        out_queue.put(("done", worker_id))
    except Exception:  # surface the error WITH its stack to the consumer
        import traceback
        out_queue.put(("err", f"worker {worker_id}:\n{traceback.format_exc()}"))
class DataLoader:
    """Batch loader with optional multiprocess workers.

    Reference: python/paddle/io/dataloader/{dataloader_iter,worker}.py with
    shared-memory tensor transport (mmap_allocator.cc — SURVEY.md §2.5).
    ``num_workers>0`` on a map-style dataset forks real worker processes:
    batch i goes to worker i % num_workers and an ordering buffer restores
    sequence on the consumer side (the reference's scheme). Transport is
    pickle over an OS pipe — numpy arrays ride the zero-copy pickle-5
    buffer protocol, the portable analog of the reference's shm segments.
    IterableDataset (not index-addressable) uses a prefetch thread."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("length of IterableDataset-backed loader is unknown")
        return len(self.batch_sampler)

    def _iter_batches(self):
        # Profiler hook (reference: RecordEvent in dataloader, SURVEY §5.1)
        from ..profiler.record import host_recorder, RecordEvent

        def _record(make):
            if not host_recorder.enabled:
                return make()
            with RecordEvent("DataLoader", "Dataloader"):
                return make()

        if self._iterable:
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield _record(lambda: self.collate_fn(batch))
                    batch = []
            if batch and not self.drop_last:
                yield _record(lambda: self.collate_fn(batch))
            return
        for idx_batch in self.batch_sampler:
            yield _record(lambda: self.collate_fn(
                [self.dataset[i] for i in idx_batch]))

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._iter_batches()
            return
        if not self._iterable:
            yield from self._iter_multiprocess()
            return
        # IterableDataset: prefetch thread (no index addressing to split on)
        q: "queue.Queue" = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item

    def _iter_multiprocess(self):
        """Real worker processes, reference ordering scheme: batch b is
        produced by worker b % num_workers; a reorder buffer keeps output
        in batch order while workers run ahead up to prefetch_factor."""
        import multiprocessing as mp
        # spawn, not fork: the parent holds live jax/XLA threads and forking
        # a multithreaded process deadlocks (observed, and warned by jax).
        # Workers do host-side numpy only, so a fresh interpreter is correct;
        # dataset/collate_fn must be picklable (same rule as the reference's
        # spawn-mode dataloader).
        ctx = mp.get_context("spawn")
        nw = self.num_workers
        idx_queues = [ctx.Queue() for _ in range(nw)]
        out_queue = ctx.Queue(maxsize=nw * self.prefetch_factor)
        # workers collate to numpy; Tensor wrapping happens on this side
        worker_collate = (numpy_collate_fn
                          if self.collate_fn is default_collate_fn
                          else self.collate_fn)
        wrap = (self.collate_fn is default_collate_fn)
        workers = [
            ctx.Process(
                target=_worker_loop,
                args=(self.dataset, worker_collate, idx_queues[w], out_queue,
                      self._worker_init_fn, w, nw),
                daemon=True)
            for w in range(nw)
        ]
        # Children must never dial the accelerator — including during
        # bootstrap arg-unpickling (a dataset holding jax arrays would
        # initialize a backend before _worker_loop's own guard runs), so
        # the platform pin goes into the env the children inherit.
        saved_platform = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            for p in workers:
                p.start()
        finally:
            if saved_platform is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = saved_platform
        batches = list(self.batch_sampler)
        try:
            # prime + stream the index queues
            for b, idxs in enumerate(batches):
                idx_queues[b % nw].put((b, idxs))
            for q in idx_queues:
                q.put(None)  # per-worker end marker
            buffer = {}
            next_out = 0
            n = len(batches)
            while next_out < n:
                try:
                    kind, payload = out_queue.get(timeout=5.0)
                except queue.Empty:
                    # don't block forever on silently-dead workers (e.g. a
                    # spawn child that crashed before reaching the loop)
                    dead = [w for w, p in enumerate(workers)
                            if not p.is_alive() and p.exitcode != 0]
                    if dead:
                        raise RuntimeError(
                            f"DataLoader worker(s) {dead} died with exit "
                            f"codes {[workers[w].exitcode for w in dead]}")
                    if all(not p.is_alive() for p in workers):
                        raise RuntimeError(
                            "DataLoader workers exited before producing all "
                            "batches")
                    continue
                if kind == "err":
                    raise RuntimeError(f"DataLoader worker failed: {payload}")
                if kind == "done":
                    continue
                b, batch = payload
                buffer[b] = _wrap_collated(batch) if wrap else batch
                while next_out in buffer:
                    yield buffer.pop(next_out)
                    next_out += 1
        finally:
            for p in workers:
                if p.is_alive():
                    p.terminate()
            for p in workers:
                p.join(5)
