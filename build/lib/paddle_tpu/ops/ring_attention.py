"""Ring attention — blockwise attention with KV rotation over a context
(``sep``) mesh axis.

Rebuild of the reference's ring-flash-attention layer (model-zoo
ring_flash_attention.py consuming core sep groups + batch_isend_irecv —
SURVEY.md §5.7 mechanism 3), designed TPU-first: the KV block rotates around
the ICI ring via ``lax.ppermute`` (XLA double-buffers the permute against the
block computation), and per-block results merge with online-softmax (log-sum-
exp) rescaling, so memory stays O(S_local) per device while attending to the
full sequence. Complements the Ulysses all_to_all variant (models/llama.py);
pick per config (`sep_mode`).

Causality uses *global* positions: device i holds contiguous chunk i, so a KV
block that originated at chunk j is fully visible when j < i, causal when
j == i, and fully masked when j > i.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ..core.dispatch import apply
from ..parallel import mesh as _mesh

_NEG_INF = -1e30


def _block_attn(q, k, v, bias, scale):
    """(B,H,Sq,D)x(B,H,Sk,D) -> normalized out (B,H,Sq,D), lse (B,H,Sq).
    fp32 softmax accumulation; bias is additive (0 / -inf mask)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + bias
    m = jnp.max(s, axis=-1)
    # fully-masked rows: keep m finite so exp() stays 0 without NaNs
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    # floor keeps 1/l^2 in the divide's gradient finite in fp32 (a 1e-30
    # floor overflows to inf and poisons the backward with 0*inf NaNs)
    l_safe = jnp.maximum(l, 1e-12)
    lse = jnp.where(l > 0, m_safe + jnp.log(l_safe), _NEG_INF)
    out = out / l_safe[..., None]
    return out, lse


def _merge(out1, lse1, out2, lse2):
    """Online-softmax merge of two normalized partial results. Fully-masked
    sides carry lse = -1e30 (finite), so their weight underflows to exactly 0
    and the other side's weight to 1 — no extra guarding needed."""
    lse_new = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse_new)
    w2 = jnp.exp(lse2 - lse_new)
    return out1 * w1[..., None] + out2 * w2[..., None], lse_new


def ring_attention_array(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Per-device blockwise ring attention, called inside shard_map.

    q, k, v: (B, S_local, H, D) paddle layout (GQA: H_kv may divide H).
    Returns (B, S_local, H, D).
    """
    b, s_loc, hq, d = q.shape
    hk = k.shape[2]
    rep = hq // hk
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    # (B, H, S, D) internal layout; KV rotates with its ORIGINAL hk heads —
    # the GQA head repeat happens per-round after the permute, so ring ICI
    # traffic is not inflated by hq/hk
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    p_size = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    q_pos = my * s_loc + jnp.arange(s_loc)
    acc = jnp.zeros((b, hq, s_loc, d), jnp.float32)
    lse = jnp.full((b, hq, s_loc), _NEG_INF, jnp.float32)

    kv = (kt, vt)
    for r in range(p_size):
        src = (my - r) % p_size  # chunk id currently held

        def compute(kv_pair):
            kr, vr = kv_pair
            if rep != 1:
                kr = jnp.repeat(kr, rep, axis=1)
                vr = jnp.repeat(vr, rep, axis=1)
            if causal:
                k_pos = src * s_loc + jnp.arange(s_loc)
                bias = jnp.where(k_pos[None, :] <= q_pos[:, None],
                                 0.0, _NEG_INF)[None, None]
            else:
                bias = jnp.zeros((1, 1, s_loc, s_loc), jnp.float32)
            return _block_attn(qt, kr, vr, bias, scale)

        def skip(kv_pair):
            return (jnp.zeros((b, hq, s_loc, d), jnp.float32),
                    jnp.full((b, hq, s_loc), _NEG_INF, jnp.float32))

        if causal:
            # chunks strictly ahead of this device are fully masked: skip
            # both matmuls (their result is all-zero / -inf anyway)
            out_r, lse_r = lax.cond(src > my, skip, compute, kv)
        else:
            out_r, lse_r = compute(kv)
        acc, lse = _merge(acc, lse, out_r, lse_r)
        if r + 1 < p_size:
            kv = tuple(lax.ppermute(t, axis_name, perm) for t in kv)

    return acc.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_flash_attention(query, key, value, group=None, causal: bool = True,
                         scale: Optional[float] = None, axis: str = "sep"):
    """Eager/global-array entry: inputs (B, S, H, D) with S the FULL
    sequence; runs the ring program over the mesh's ``sep`` (context) axis
    and returns the full-sequence result. Differentiable (tape-recorded)."""
    mesh = _mesh.ensure_mesh() if group is None else group.mesh
    ax = getattr(group, "axis", axis)
    deg = mesh.shape.get(ax, 1)

    def fn(qv, kv, vv):
        if deg <= 1:
            from . import flash_attention as fa
            return fa._sdpa_array(qv, kv, vv, scale=scale or
                                  1.0 / math.sqrt(qv.shape[-1]), causal=causal)
        prog = shard_map(
            partial(ring_attention_array, axis_name=ax, causal=causal,
                    scale=scale),
            mesh=mesh, in_specs=(P(None, ax), P(None, ax), P(None, ax)),
            out_specs=P(None, ax), check_vma=False)
        return prog(qv, kv, vv)

    return apply(fn, query, key, value, op_name="ring_flash_attention")
