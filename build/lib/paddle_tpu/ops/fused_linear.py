"""Fused dW GEMM + in-place gradient accumulation — Pallas TPU kernel.

Rebuild of the reference's ``fused_linear_param_grad_add`` CUDA kernel
(paddle/phi/kernels/fusion/gpu/fused_linear_param_grad_add_kernel.cu:§0,
exposed as ``paddle._C_ops.fused_linear_param_grad_add`` — SURVEY.md §2.2).
In the reference it fuses the weight-gradient GEMM with the add into the
fp32 ``main_grad`` accumulation buffer, removing a separate elementwise add
in sharded / pipeline grad-accumulation loops.

TPU-native design: a tiled Pallas matmul whose output block is *initialised
from the existing accumulator* and donated (``input_output_aliases``), so the
accumulate never materialises ``x^T @ dout`` separately. Accumulation is
always fp32 (main_grad semantics) regardless of activation dtype. An XLA
fallback (``acc + einsum``) is the numerics oracle; XLA's own fusion makes it
near-optimal too, so the flag-gated Pallas path is about guaranteed in-place
behaviour at large weight shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import use_pallas


def _pick(n: int, cands=(512, 256, 128)) -> int | None:
    for b in cands:
        if n % b == 0:
            return b
    return None


def _grad_add_kernel(x_ref, g_ref, acc_ref, out_ref, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = acc_ref[...]

    x = x_ref[...]          # (bk, bi) rows × in-features tile
    g = g_ref[...]          # (bk, bo) rows × out-features tile
    out_ref[...] += jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _pallas_grad_add(x2, g2, acc):
    rows, din = x2.shape
    dout = g2.shape[1]
    bk = _pick(rows)
    bi = _pick(din, (256, 128))
    bo = _pick(dout, (256, 128))
    grid = (din // bi, dout // bo, rows // bk)
    return pl.pallas_call(
        functools.partial(_grad_add_kernel, nk=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bk, bi), lambda i, j, k: (k, i)),
            pl.BlockSpec((bk, bo), lambda i, j, k: (k, j)),
            pl.BlockSpec((bi, bo), lambda i, j, k: (i, j)),
        ],
        out_specs=pl.BlockSpec((bi, bo), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((din, dout), jnp.float32),
        input_output_aliases={2: 0},
    )(x2, g2, acc)


def _pallas_ok(rows, din, dout):
    return (use_pallas() and _pick(rows) is not None
            and _pick(din, (256, 128)) is not None
            and _pick(dout, (256, 128)) is not None)


def fused_linear_param_grad_add(x, dout, dweight=None, dbias=None,
                                multi_precision: bool = True,
                                has_bias: bool = True):
    """Accumulate ``dweight += x^T @ dout`` (and ``dbias += sum(dout)``).

    ``x``: (..., din) activations, ``dout``: (..., dout) output grad.
    ``dweight``/``dbias`` are the running accumulators (fp32 when
    ``multi_precision``, the reference's main_grad); None means start at zero.
    Returns ``(dweight, dbias)`` (dbias None when ``has_bias=False``).
    """
    din = x.shape[-1]
    dO = dout.shape[-1]
    rows = x.size // din
    x2 = x.reshape(rows, din)
    g2 = dout.reshape(rows, dO)
    acc_dtype = jnp.float32 if multi_precision else x2.dtype
    if dweight is None:
        dweight = jnp.zeros((din, dO), acc_dtype)
    else:
        dweight = jnp.asarray(dweight, acc_dtype)
    if multi_precision and _pallas_ok(rows, din, dO):
        dw = _pallas_grad_add(x2, g2, dweight)
    else:
        dw = dweight + jax.lax.dot_general(
            x2, g2, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dtype).astype(acc_dtype)
    db = None
    if has_bias:
        db_new = g2.astype(acc_dtype).sum(axis=0)
        db = db_new if dbias is None else jnp.asarray(dbias, acc_dtype) + db_new
    return dw, db


def linear_with_main_grad(x, w, b=None):
    """Linear whose custom vjp routes dW through the fused accumulate path.

    Forward: ``y = x @ w (+ b)``. Backward returns fp32 dW/db computed by
    :func:`fused_linear_param_grad_add` (single fused GEMM, fp32 accumulate),
    matching the reference's main_grad discipline under grad-accumulation.
    """
    return _linear_mg(x, w, b)


@jax.custom_vjp
def _linear_mg(x, w, b):
    y = x @ w
    if b is not None:
        y = y + b
    return y


def _linear_mg_fwd(x, w, b):
    return _linear_mg(x, w, b), (x, w, b is not None)


def _linear_mg_bwd(res, g):
    x, w, has_b = res
    dx = (g @ w.T.astype(g.dtype)).astype(x.dtype)
    dw, db = fused_linear_param_grad_add(x, g, has_bias=has_b)
    return dx, dw.astype(w.dtype), (db if has_b else None)


_linear_mg.defvjp(_linear_mg_fwd, _linear_mg_bwd)
