"""Rotary position embedding (fused_rope equivalent).

Reference: fused_rope CUDA kernel family under paddle/fluid/operators/fused/
(SURVEY.md §2.2 "other fused family"). On TPU, rope is a cheap elementwise op
that XLA fuses into the surrounding attention projections, so the XLA form IS
the fused form; a Pallas variant adds nothing measurable.

Convention: NeoX/Llama half-rotation. Layout (B, S, H, D).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def build_rope_cache(seq_len: int, head_dim: int, base: float = 10000.0,
                     dtype=jnp.float32, position_offset: int = 0):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(position_offset, position_offset + seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def apply_rope_array(q, k, cos, sin):
    """q, k: (B, S, H, D); cos/sin: (S, D) or (B, S, D)."""
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    else:
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    q_out = qf * cos + _rotate_half(qf) * sin
    k_out = kf * cos + _rotate_half(kf) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)


def fused_rotary_position_embedding(q: Tensor, k: Tensor, cos, sin):
    """Parity with paddle.incubate.nn.functional.fused_rotary_position_embedding."""
    cos_v = cos._value if isinstance(cos, Tensor) else cos
    sin_v = sin._value if isinstance(sin, Tensor) else sin
    return apply(lambda a, b: apply_rope_array(a, b, cos_v, sin_v), q, k,
                 op_name="fused_rope")
