"""TPU kernel library (Pallas + XLA fallbacks).

This package is the rebuild of the reference's fused CUDA kernel corpus
(SURVEY.md §2.2): flash_attn → flash_attention.py, rms_norm → rms_norm.py,
fused_rope → rope.py, ring attention (PaddleNLP ring_flash_attention) →
ring_attention.py, fused_linear_param_grad_add → fused_linear.py,
MoE global_scatter/gather + capacity → moe_ops.py,
fused_multi_transformer → fused_transformer_block.py.

Every kernel has: a Pallas TPU path, an XLA (jnp) reference path used on CPU
and as the numerics oracle in tests, and a custom_vjp so both paths are
differentiable. Selection honours FLAGS_use_pallas_kernels.
"""

from . import flash_attention, rms_norm, rope, moe_ops, ring_attention  # noqa: F401
from . import fused_linear, fused_transformer_block  # noqa: F401
from . import paged_attention  # noqa: F401
