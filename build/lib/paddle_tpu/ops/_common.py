"""Shared helpers for the kernel library."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..flags import flag_value


# Platform strings that are NOT a TPU. The axon PJRT plugin registers the
# real chip under platform "axon" (xla_bridge warns "Platform 'axon' is
# experimental"), so membership is tested negatively: any accelerator that
# is not a CPU/GPU-family backend is treated as a TPU for kernel selection.
_NON_TPU_PLATFORMS = ("cpu", "gpu", "cuda", "rocm", "metal", "interpreter")


def is_tpu_platform(platform: str) -> bool:
    """Single source of the platform policy (bench.py reuses it)."""
    return platform not in _NON_TPU_PLATFORMS


@functools.lru_cache(maxsize=1)
def on_tpu() -> bool:
    try:
        return is_tpu_platform(jax.devices()[0].platform)
    except Exception:
        return False


def use_pallas() -> bool:
    return on_tpu() and flag_value("use_pallas_kernels")


def next_multiple(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m
