"""Flash attention (forward + backward) — Pallas TPU kernels + XLA fallback.

Rebuild of the reference's ``flash_attn`` path: CUDA glue
paddle/phi/kernels/gpu/flash_attn_kernel.cu + vendored libflashattn, Python
surface python/paddle/nn/functional/flash_attention.py (SURVEY.md §2.2).
Here the kernel itself is written in Pallas (online-softmax tiling over KV
blocks; fp32 accumulators in VMEM; LSE saved for the backward pass), which is
the TPU-native equivalent of FlashAttention-2.

Internal layout: (BH, S, D) with batch*heads flattened into the leading grid
dimension. Public entry points accept the paddle layout (B, S, H, D).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import use_pallas
from ..core.dispatch import apply, unwrap
from ..core.tensor import Tensor
from .. import random as _random

_NEG_INF = -1e30


def _mult(a: int, b: int) -> bool:
    return a % b == 0


# ===========================================================================
# Forward kernel
# ===========================================================================
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, bq, bk, nkv):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (j * bk < (i + 1) * bq) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (BQ, BK)
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(row >= col, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nkv - 1)
    def _finalize():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        # lse is carried as (BH, 1, S): a lane-major row per bh so the block
        # shape (1, 1, bq) satisfies Mosaic's (sublane, lane) tiling rule.
        lse_ref[0, 0] = (m + jnp.log(safe_l))[:, 0]


def _flash_fwd_pallas(q, k, v, scale, causal, bq, bk):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nkv = sq // bq, sk // bk
    grid = (bh, nq, nkv)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nkv=nkv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
    )(q, k, v)
    return out, lse[:, 0]


# ===========================================================================
# Backward kernels
# ===========================================================================
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, bq, bk, nkv):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (j * bk < (i + 1) * bq) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - lse)
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(row >= col, p, 0.0)
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == nkv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, bq, bk, nq):
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = ((i + 1) * bq > j * bk) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - lse)
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            p = jnp.where(row >= col, p, 0.0)
        pt = p.astype(do.dtype)
        dv_acc[...] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32) * scale

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal, bq, bk):
    bh, sq, d = q.shape
    sk = k.shape[1]
    nq, nkv = sq // bq, sk // bk
    # lse/delta travel as (BH, 1, S) — see _fwd_kernel note on Mosaic tiling.
    lse3 = lse[:, None, :]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nkv=nkv),
        grid=(bh, nq, nkv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
    )(q, k, v, g, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq),
        grid=(bh, nkv, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
    )(q, k, v, g, lse3, delta)
    return dq, dk, dv


# ===========================================================================
# XLA reference path (oracle + fallback), layout (BH, S, D)
# ===========================================================================
def _attn_ref(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


# ===========================================================================
# custom_vjp dispatcher
# ===========================================================================
def _pick_blocks(sq, sk):
    def pick(s):
        for b in (512, 256, 128):
            if s % b == 0:
                return b
        return None
    return pick(sq), pick(sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_bhsd(q, k, v, scale, causal):
    """(BH, S, D) flash attention; differentiable; pallas on TPU."""
    out, _ = _fa_fwd(q, k, v, scale, causal)
    return out


def _pallas_ok(q, k):
    bq, bk = _pick_blocks(q.shape[1], k.shape[1])
    return use_pallas() and bq is not None and bk is not None and _mult(q.shape[2], 128)


def _fa_fwd(q, k, v, scale, causal):
    if _pallas_ok(q, k):
        bq, bk = _pick_blocks(q.shape[1], k.shape[1])
        out, lse = _flash_fwd_pallas(q, k, v, scale, causal, bq, bk)
        return out, (q, k, v, out, lse)
    out = _attn_ref(q, k, v, scale, causal)
    return out, (q, k, v, out, None)


def _fa_bwd(scale, causal, res, g):
    q, k, v, out, lse = res
    if lse is not None and _pallas_ok(q, k):
        bq, bk = _pick_blocks(q.shape[1], k.shape[1])
        return _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal, bq, bk)
    _, vjp = jax.vjp(lambda a, b, c: _attn_ref(a, b, c, scale, causal), q, k, v)
    return vjp(g)


flash_attention_bhsd.defvjp(_fa_fwd, _fa_bwd)


# ===========================================================================
# Public paddle-layout entry points
# ===========================================================================
def _sdpa_array(q, k, v, *, scale, causal):
    """(B, S, H, D) in/out; handles GQA by repeating KV heads."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hq, k.shape[1], d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hq, v.shape[1], d)
    out = flash_attention_bhsd(qt, kt, vt, scale, causal)
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)


def _sdpa_masked(q, k, v, mask, *, scale, dropout_p, dropout_key, causal):
    """XLA path with arbitrary mask / dropout. (B, S, H, D)."""
    hq, hk = q.shape[2], k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(cm, s, _NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            s = jnp.where(mask, s, _NEG_INF)
        else:
            s = s + mask.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def scaled_dot_product_attention(query: Tensor, key: Tensor, value: Tensor,
                                 attn_mask=None, dropout_p=0.0, is_causal=False,
                                 training=True, scale=None):
    """Paddle-layout (B, S, H, D) attention. Reference surface:
    python/paddle/nn/functional/flash_attention.py (SURVEY.md §2.2)."""
    d = query.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    drop = dropout_p if training else 0.0
    if attn_mask is None and drop == 0.0:
        return apply(lambda a, b, c: _sdpa_array(a, b, c, scale=sc, causal=is_causal),
                     query, key, value, op_name="flash_attention")
    dkey = _random.next_key()
    if attn_mask is not None:
        return apply(
            lambda a, b, c, m: _sdpa_masked(a, b, c, m, scale=sc, dropout_p=drop,
                                            dropout_key=dkey, causal=is_causal),
            query, key, value, attn_mask if isinstance(attn_mask, Tensor) else Tensor(attn_mask),
            op_name="attention_masked")
    return apply(
        lambda a, b, c: _sdpa_masked(a, b, c, None, scale=sc, dropout_p=drop,
                                     dropout_key=dkey, causal=is_causal),
        query, key, value, op_name="attention_dropout")
