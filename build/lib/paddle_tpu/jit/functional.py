"""Functional bridge: imperative Layers ⇄ pure jax functions.

This is the load-bearing piece that replaces the reference's executors
(StandaloneExecutor/ProgramInterpreter, paddle/fluid/framework/new_executor/ —
SURVEY.md §2.1): instead of interpreting an op graph, we *trace* the user's
imperative code (which runs on the vjp tape) under jax.jit, with Parameters and
buffers temporarily rebound to traced values. XLA then owns scheduling, fusion,
memory and collectives.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from ..nn.layer import Layer


def param_arrays(layer: Layer, trainable_only: bool = False) -> Dict[str, Any]:
    out = {}
    for name, p in layer.named_parameters():
        if trainable_only and not p.trainable:
            continue
        out[name] = p._value
    return out


def buffer_arrays(layer: Layer) -> Dict[str, Any]:
    out = {}
    for name, b in layer.named_buffers():
        if b is not None:
            out[name] = b._value
    return out


@contextlib.contextmanager
def bind(layer: Layer, params: Dict[str, Any] = None, buffers: Dict[str, Any] = None):
    """Temporarily point Parameters/buffers at the given (possibly traced)
    arrays; restores originals (and captures mutated buffer values) on exit."""
    param_objs = dict(layer.named_parameters())
    buffer_objs = {n: b for n, b in layer.named_buffers() if b is not None}
    saved_p = {n: p._value for n, p in param_objs.items()}
    saved_b = {n: b._value for n, b in buffer_objs.items()}
    saved_grads = {n: p._grad_value for n, p in param_objs.items()}
    mutated: Dict[str, Any] = {}
    try:
        if params is not None:
            for n, v in params.items():
                if n in param_objs:
                    param_objs[n]._value = v
        if buffers is not None:
            for n, v in buffers.items():
                if n in buffer_objs:
                    buffer_objs[n]._value = v
        yield mutated
    finally:
        for n, b in buffer_objs.items():
            mutated[n] = b._value
            b._value = saved_b[n]
        for n, p in param_objs.items():
            p._value = saved_p[n]
            p._grad_value = saved_grads[n]


def functional_call(layer: Layer, params: Dict[str, Any], *args,
                    buffers: Dict[str, Any] = None, **kwargs):
    """Call ``layer`` with parameters substituted from a pytree. Returns
    (output, new_buffers)."""
    with bind(layer, params, buffers) as mutated:
        out = layer(*args, **kwargs)
    return out, mutated


def tree_unwrap(x):
    """Recursively turn Tensors into jax arrays inside containers."""
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(tree_unwrap(e) for e in x)
    if isinstance(x, dict):
        return {k: tree_unwrap(v) for k, v in x.items()}
    return x


def tree_wrap(x):
    if isinstance(x, (jax.Array,)):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(tree_wrap(e) for e in x)
    if isinstance(x, dict):
        return {k: tree_wrap(v) for k, v in x.items()}
    return x
