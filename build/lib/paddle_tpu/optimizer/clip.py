"""Gradient clipping.

Parity with python/paddle/nn/clip.py of the reference (``ClipGradByGlobalNorm``
et al; SURVEY.md §2.5). Clip objects transform a list of (param, grad) pairs;
the hybrid-parallel variant (distributed.fleet) extends global-norm with
cross-mesh-axis psums.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp


class ClipGradBase:
    def _clip(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, jnp.clip(g, self.min, self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm

    def global_norm(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads if g is not None]
        if not sq:
            return jnp.asarray(0.0, jnp.float32)
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return jnp.sqrt(total)

    def _clip(self, params_grads):
        clippable = [(p, g) for p, g in params_grads
                     if g is not None and getattr(p, "need_clip", True)]
        gnorm = self.global_norm([g for _, g in clippable])
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out
