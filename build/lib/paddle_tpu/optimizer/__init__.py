from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Lamb, RMSProp, Adagrad,
)
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm  # noqa: F401
from . import lr  # noqa: F401
