"""Learning-rate schedulers.

Parity with python/paddle/optimizer/lr.py of the reference (SURVEY.md §2.5
optimizers row). Host-side scalar schedules; compiled train steps receive the
current lr as a traced scalar argument.
"""

from __future__ import annotations

import math
from typing import List, Optional


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.last_lr = learning_rate
        self.verbose = verbose
        self.step()

    def get_lr(self) -> float:
        raise NotImplementedError

    def step(self, epoch: Optional[int] = None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def __call__(self) -> float:
        return self.last_lr

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state.get("last_epoch", self.last_epoch)
        self.last_lr = state.get("last_lr", self.last_lr)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return self.base_lr * (self.d_model ** -0.5) * min(
            step ** -0.5, step * self.warmup_steps ** -1.5)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries: List[int], values: List[float], last_epoch=-1,
                 verbose=False):
        self.boundaries = boundaries
        self.values = values
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / self.decay_steps) if step > 0 else 1
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return (self.base_lr - self.end_lr) * (
            (1 - step / decay_steps) ** self.power) + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1,
                 verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.final_lr = learning_rate if not isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * (
                self.last_epoch / self.warmup_steps) + self.start_lr
        if self.lr_sched is not None:
            self.lr_sched.step(self.last_epoch - self.warmup_steps)
            return self.lr_sched()
        return self.final_lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = milestones
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = len([m for m in self.milestones if m <= self.last_epoch])
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.cooldown_counter = 0
        self.num_bad_epochs = 0
        self.base_lr = learning_rate
        self.last_lr = learning_rate
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        self.last_epoch += 1
        if metrics is None:
            return
        current = float(metrics)
        if self.best is None:
            self.best = current
            return
        better = (current < self.best - self._thresh()) if self.mode == "min" \
            else (current > self.best + self._thresh())
        if better:
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0

    def _thresh(self):
        if self.threshold_mode == "rel":
            return builtins_abs(self.best) * self.threshold if self.best else self.threshold
        return self.threshold


def builtins_abs(x):
    return x if x >= 0 else -x


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos",
                 three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        super().__init__(self.initial_lr, last_epoch, verbose)

    def get_lr(self):
        up_steps = int(self.total_steps * self.phase_pct)
        step = min(self.last_epoch, self.total_steps)
        if step <= up_steps and up_steps > 0:
            pct = step / up_steps
            return self.initial_lr + (self.max_lr - self.initial_lr) * (
                1 - math.cos(math.pi * pct)) / 2
        down = (step - up_steps) / max(self.total_steps - up_steps, 1)
        return self.end_lr + (self.max_lr - self.end_lr) * (
            1 + math.cos(math.pi * down)) / 2


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_0 = T_0
        self.T_i = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        self.T_cur = last_epoch
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = self.last_epoch
        ti = self.T_0
        while t >= ti:
            t -= ti
            ti *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * t / ti)) / 2
