"""Host-memory offload utilities.

Reference context: the recompute_hybrid offload option and the CUDA
pinned-memory staging in the allocator stack (SURVEY.md §2.4 recompute
row, §2.7 #11). On TPU, XLA owns HBM; what the framework controls is
*placement*: arrays can live in the host-side pinned buffer
(``memory_kind="pinned_host"``) and stream back over PCIe when needed —
activation offload for long-sequence training, optimizer-state offload
for memory-bound fine-tuning.

CPU backend has no memory kinds; there the offload degrades to a host
numpy copy (still releases the "device" buffer), keeping tests and the
API portable.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

from .tensor import Tensor


def _memory_kind_supported(device) -> bool:
    try:
        return any(m.kind == "pinned_host"
                   for m in device.addressable_memories())
    except Exception:
        return False


def offload_to_host(x):
    """Move an array/Tensor to host memory, releasing its HBM footprint.

    TPU: device_put onto the pinned_host memory space of the same device
    (stays addressable by later device_puts without re-pinning).
    CPU/fallback: materialise to numpy.
    """
    v = x._value if isinstance(x, Tensor) else x
    if isinstance(v, jax.Array):
        dev = list(v.devices())[0]
        if _memory_kind_supported(dev):
            sharding = v.sharding.with_memory_kind("pinned_host")
            out = jax.device_put(v, sharding)
        else:
            # copy=True: np.asarray may alias the device buffer on the CPU
            # backend, and delete() below frees it
            out = np.array(v, copy=True)
            v.delete()
    else:
        out = np.asarray(v)
    if isinstance(x, Tensor):
        # numpy fallback stays host-side until reload_to_device; Tensor ops
        # on it would transparently re-device via jnp.asarray
        x._value = out
        return x
    return out


def reload_to_device(x, sharding: Optional[Any] = None):
    """Bring an offloaded array back to device HBM (optionally with a
    target sharding)."""
    v = x._value if isinstance(x, Tensor) else x
    if isinstance(v, jax.Array) and sharding is None:
        try:
            sharding = v.sharding.with_memory_kind("device")
        except Exception:
            sharding = None
    out = jax.device_put(v, sharding) if sharding is not None \
        else jax.device_put(v)
    if isinstance(x, Tensor):
        x._value = out
        return x
    return out


def offload_checkpoint_policy():
    """jax.checkpoint policy offloading matmul results to host instead of
    rematerialising them — the activation-offload variant of recompute
    (reference: recompute_hybrid(offload=True)). Falls back to plain
    dots-saveable when the offload policy is unavailable."""
    cp = jax.checkpoint_policies
    try:
        return cp.offload_dot_products_saveable
    except AttributeError:
        return cp.dots_saveable
