from .tensor import Tensor, Parameter  # noqa: F401
from .place import (  # noqa: F401
    Place, CPUPlace, TPUPlace, XLAPlace, CUDAPlace, set_device, get_device,
    current_place, is_compiled_with_tpu,
)
from .autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401
from . import dtype  # noqa: F401
from . import math_ops  # noqa: F401  (installs Tensor methods)
