"""Native (C++) runtime components, built on demand.

The reference keeps its runtime plumbing in C++ (SURVEY.md §2.7); here the
pieces that remain host-side (rendezvous store, …) are C++ compiled lazily
with g++ into a per-repo build dir and loaded via ctypes. Every native
component has a pure-Python fallback so the framework works without a
toolchain.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_lock = threading.Lock()


def ensure_built(stem: str) -> str | None:
    """Compile ``<stem>.cc`` into ``_build/lib<stem>.so`` (cached by mtime).

    Returns the .so path, or None when no C++ toolchain is available or the
    build fails (callers fall back to Python implementations).
    """
    src = os.path.join(_HERE, stem + ".cc")
    out = os.path.join(_BUILD_DIR, "lib" + stem + ".so")
    if not os.path.exists(src):
        return None
    with _lock:
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        gxx = shutil.which("g++")
        if gxx is None:
            return None
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # per-pid temp + atomic replace: concurrent processes may race to
        # build (single-host multi-process launch); last writer wins cleanly
        tmp = f"{out}.tmp.{os.getpid()}"
        cmd = [gxx, "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
               src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)
        except (subprocess.SubprocessError, OSError):
            if os.path.exists(out):  # another process won the race
                return out
            return None
        return out
