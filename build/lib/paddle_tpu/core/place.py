"""Device identity ("Place").

TPU-native rebuild of the reference's Place/device abstraction
(paddle/phi/common/place.h, paddle/fluid/pybind/place.cc — SURVEY.md §2.1).
The north-star asked for an ``XLAPlace`` beside ``CUDAPlace``; here the whole
framework is the XLA backend, so ``TPUPlace`` (aliased ``XLAPlace``) is the
accelerator place and maps onto a ``jax.Device``. A Place may also carry the
notion of "current mesh" implicitly via paddle_tpu.parallel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax


class Place:
    """Base device identity. Equality is by (kind, index)."""

    kind = "undefined"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.kind, self._device_id))

    def __repr__(self):
        return f"Place({self.kind}:{self._device_id})"

    # -- jax bridge ---------------------------------------------------------
    def jax_device(self) -> Optional[jax.Device]:
        devs = [d for d in jax.devices() if _platform_of(d) == self.kind]
        if not devs:
            devs = jax.devices()  # fall back to whatever the host has
        return devs[self._device_id % len(devs)]


def _platform_of(dev: jax.Device) -> str:
    p = dev.platform
    return {"cpu": "cpu", "tpu": "tpu", "gpu": "gpu"}.get(p, p)


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    kind = "tpu"


# The north-star name: an XLA-backed accelerator place.
XLAPlace = TPUPlace


class CUDAPlace(Place):
    """Accepted for API compatibility; resolves to whatever accelerator exists."""

    kind = "gpu"


_current_place: list = [None]


@functools.lru_cache(maxsize=None)
def _default_place() -> Place:
    platforms = {d.platform for d in jax.devices()}
    if "tpu" in platforms:
        return TPUPlace(0)
    if "gpu" in platforms:
        return CUDAPlace(0)
    return CPUPlace(0)


def set_device(device: str) -> Place:
    """``set_device("tpu")`` / ``"tpu:0"`` / ``"cpu"`` — parity with paddle.set_device."""
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    cls = {"cpu": CPUPlace, "tpu": TPUPlace, "xla": TPUPlace, "gpu": CUDAPlace}.get(name)
    if cls is None:
        raise ValueError(f"Unknown device {device!r}")
    _current_place[0] = cls(idx)
    return _current_place[0]


def get_device() -> str:
    p = _current_place[0] or _default_place()
    return f"{p.kind}:{p.get_device_id()}"


def current_place() -> Place:
    return _current_place[0] or _default_place()


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())
