"""``paddle_tpu.autograd`` — public autograd surface.

Parity with python/paddle/autograd/ of the reference (backward, grad, PyLayer
— SURVEY.md §2.1 eager autograd row).
"""

from ..core.autograd import backward, grad, no_grad, enable_grad, set_grad_enabled  # noqa: F401
from ..core.dispatch import apply as _apply
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """Custom differentiable op, parity with paddle.autograd.PyLayer.

    Subclasses define ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    operating on Tensors. Implemented over jax.custom_vjp-free tape nodes:
    the backward is recorded directly as a GradNode.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd as ag
        import jax.numpy as jnp

        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)

        needs_grad = ag.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if not needs_grad:
            return outs if single else list(outs_t)

        import jax
        avals = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype) for o in outs_t]

        def vjp_fn(cots):
            gs = cls.backward(ctx, *[Tensor(c) for c in cots])
            gs = (gs,) if isinstance(gs, Tensor) else tuple(gs)
            out = []
            gi = 0
            for a in args:
                if isinstance(a, Tensor):
                    g = gs[gi] if gi < len(gs) else None
                    gi += 1
                    out.append(None if g is None else g._value)
            return tuple(out)

        node = ag.GradNode(vjp_fn, tensor_inputs, avals, name=cls.__name__)
        wrapped = tuple(
            Tensor(o._value, stop_gradient=False, _grad_node=node, _out_index=i)
            for i, o in enumerate(outs_t))
        return wrapped[0] if single else list(wrapped)
