"""hapi callbacks (reference: python/paddle/hapi/callbacks.py:§0 —
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL,
ReduceLROnPlateau)."""

from __future__ import annotations

import numbers
import os
import warnings
from typing import List, Optional

import numpy as np

from .progressbar import ProgressBar


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, ModelCheckpoint) for c in cbks) and save_dir:
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or ["loss"],
    })
    return lst


class CallbackList:
    def __init__(self, callbacks: Optional[List["Callback"]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cbk):
        self.callbacks.append(cbk)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            fn = getattr(c, name, None)
            if fn is not None:
                fn(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    # train/eval/predict begin|end; epoch begin|end; batch begin|end
    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.train_metrics = self.params.get("metrics", ["loss"])

    def on_epoch_begin(self, epoch, logs=None):
        self.steps = self.params.get("steps")
        self.epoch = epoch
        self.train_step = 0
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.train_progbar = ProgressBar(num=self.steps, verbose=self.verbose)

    def _updates(self, logs, bar):
        values = [(k, logs[k]) for k in self.train_metrics if k in logs]
        bar.update(self.train_step, values)

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        self.train_step += 1
        if self.verbose and self.train_step % self.log_freq == 0:
            self._updates(logs, self.train_progbar)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose and logs:
            self._updates(logs, self.train_progbar)

    def on_eval_begin(self, logs=None):
        logs = logs or {}
        self.eval_steps = logs.get("steps")
        self.eval_metrics = logs.get("metrics", ["loss"])
        self.eval_step = 0
        self.eval_progbar = ProgressBar(num=self.eval_steps, verbose=self.verbose)
        if self.verbose:
            print("Eval begin...")

    def on_eval_batch_end(self, step, logs=None):
        logs = logs or {}
        self.eval_step += 1
        if self.verbose and self.eval_step % self.log_freq == 0:
            values = [(k, logs[k]) for k in self.eval_metrics if k in logs]
            self.eval_progbar.update(self.eval_step, values)

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.verbose:
            values = [(k, logs[k]) for k in getattr(self, "eval_metrics", [])
                      if k in logs]
            self.eval_progbar.update(self.eval_step, values)
            print("Eval samples: ", logs.get("samples", ""))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler; by_step (default) or by_epoch."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None) if self.model else None
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            warnings.warn(f"EarlyStopping mode {mode} unknown, using 'auto'")
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in self.monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        self.best_value = self.baseline if self.baseline is not None else (
            np.inf if self.monitor_op == np.less else -np.inf)

    def on_epoch_end(self, epoch, logs=None):
        self.stopped_epoch = epoch

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            warnings.warn(f"Monitor of EarlyStopping should be loss or metric "
                          f"name; {self.monitor} missing in eval logs")
            return
        if isinstance(current, (list, tuple, np.ndarray)):
            current = float(np.asarray(current).ravel()[0])
        if self.monitor_op(current - self.min_delta, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.model is not None:
                self.best_weights = {
                    k: np.array(np.asarray(v._value))
                    for k, v in self.model.network.state_dict().items()}
        else:
            self.wait_epoch += 1
        if self.wait_epoch >= self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"Epoch {self.stopped_epoch + 1}: early stopping")

    def on_train_end(self, logs=None):
        # restore the best-seen weights (reference persists best_model;
        # in-memory restore keeps the semantics without a save_dir)
        if self.save_best_model and self.best_weights is not None:
            self.model.network.set_state_dict(self.best_weights)


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.wait = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = lambda a, b: np.greater(a, b + self.min_delta)
            self.best = -np.inf
        else:
            self.monitor_op = lambda a, b: np.less(a, b - self.min_delta)
            self.best = np.inf

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        if isinstance(current, (list, tuple, np.ndarray)):
            current = float(np.asarray(current).ravel()[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.monitor_op(current, self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None and not hasattr(opt._learning_rate, "step"):
                    old = float(opt.get_lr())
                    new = max(old * self.factor, self.min_lr)
                    if old - new > 1e-12:
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old:g} -> {new:g}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar logger. VisualDL itself is unavailable offline; scalars are
    appended to a plain-text log under ``log_dir`` (one line per step),
    keeping the callback surface."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, mode, logs):
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, f"{mode}.log")
        with open(path, "a") as f:
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    f.write(f"{self._step}\t{k}\t{v}\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)
