"""``paddle_tpu.hapi`` — high-level Model API (reference:
python/paddle/hapi/ — SURVEY.md §2.5 hapi row)."""

from .model import Model, summary  # noqa: F401
from . import callbacks  # noqa: F401
from .progressbar import ProgressBar  # noqa: F401
