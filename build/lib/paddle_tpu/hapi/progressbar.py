"""Terminal progress bar for hapi (reference:
python/paddle/hapi/progressbar.py:§0)."""

from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self.file = file
        self._last_len = 0
        self._start = time.time() if start else None

    def start(self):
        self._start = time.time()

    def update(self, current_num, values=None):
        values = values or []
        msg = f"step {current_num}"
        if self._num:
            msg += f"/{self._num}"
        if self._start is not None and current_num:
            per = (time.time() - self._start) / current_num
            unit = "s/step" if per >= 1 else "ms/step"
            msg += f" - {per if per >= 1 else per * 1e3:.0f}{unit}"
        for k, v in values:
            if isinstance(v, (list, tuple)):
                body = " ".join(f"{x:.4f}" for x in v)
            elif isinstance(v, float):
                body = f"{v:.4f}"
            else:
                body = str(v)
            msg += f" - {k}: {body}"
        if self._verbose == 1:
            pad = max(self._last_len - len(msg), 0)
            self.file.write("\r" + msg + " " * pad)
            if self._num and current_num >= self._num:
                self.file.write("\n")
            self._last_len = len(msg)
        elif self._verbose == 2:
            self.file.write(msg + "\n")
        self.file.flush()
