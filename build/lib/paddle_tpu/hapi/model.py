"""``paddle_tpu.Model`` — high-level train/eval/predict loop.

Rebuild of python/paddle/hapi/model.py:§0 (SURVEY.md §2.5 hapi row). The
reference routes through either the dygraph or static-graph engine; here the
engine is the eager jax path (Layer call + autograd tape + optimizer.step),
with the compiled jit.TrainStep available for the hot path via
``Model.prepare(..., jit_compile=True)`` — the TPU analog of the reference's
``paddle.jit.to_static`` switch.
"""

from __future__ import annotations

import os
import warnings
from typing import List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor
from ..framework import io_save
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import config_callbacks

__all__ = ["Model", "summary"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


def _batch_len(x, default):
    """Leading-dim size of a batch element (Tensor or numpy)."""
    try:
        v = x._value if isinstance(x, Tensor) else x
        return int(np.asarray(v).shape[0])
    except Exception:
        return default


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._train_step = None  # compiled TrainStep when jit_compile=True

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit_compile: bool = False):
        self._train_step = None  # re-prepare drops any old compiled step
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be a Layer or a callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle_tpu.metric.Metric")
        self._amp_configs = amp_configs
        if jit_compile:
            from ..jit import TrainStep
            if self._metrics:
                warnings.warn(
                    "jit_compile=True: train-loop metrics are not computed "
                    "inside the compiled step (evaluate() still reports them)")
            loss_fn = self._loss
            model_self = self

            def step_loss(model, *batch):
                # _jit_n_labels is pinned by train_batch before the first
                # call, i.e. before jax traces this function
                n = model_self._jit_n_labels
                outs = _to_list(model(*batch[:-n] if n else batch))
                labs = list(batch[-n:]) if n else []
                losses = _to_list(loss_fn(*(outs + labs)))
                total = losses[0]
                for extra in losses[1:]:
                    total = total + extra
                return total

            self._jit_n_labels = None
            self._train_step = TrainStep(self.network, step_loss, optimizer)
        return self

    def parameters(self):
        return self.network.parameters()

    # -- single-batch paths ---------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(y) for y in _to_list(labels)]
        if self._train_step is not None:
            if not update:
                raise ValueError(
                    "gradient accumulation (update=False) is not supported "
                    "with jit_compile=True; fold accumulation into the "
                    "compiled step or use the eager path")
            if self._jit_n_labels is None:
                self._jit_n_labels = len(labels)
            elif self._jit_n_labels != len(labels):
                raise ValueError(
                    f"label count changed between jit-compiled train_batch "
                    f"calls ({self._jit_n_labels} -> {len(labels)})")
            loss = self._train_step(*inputs, *labels)
            return [float(loss)]
        outputs = self.network(*inputs)
        losses = self._loss(*(_to_list(outputs) + labels))
        losses = _to_list(losses)
        total = losses[0]
        for extra in losses[1:]:
            total = total + extra
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        self._update_metrics(outputs, labels)
        return [float(l) for l in losses]

    def eval_batch(self, inputs, labels=None):
        from ..core import no_grad
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(y) for y in _to_list(labels)]
        with no_grad():
            outputs = self.network(*inputs)
            losses = []
            if self._loss is not None:
                losses = [float(l) for l in
                          _to_list(self._loss(*(_to_list(outputs) + labels)))]
        self._update_metrics(outputs, labels)
        return losses

    def predict_batch(self, inputs):
        from ..core import no_grad
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        with no_grad():
            outputs = self.network(*inputs)
        return [np.asarray(o._value) for o in _to_list(outputs)]

    def _update_metrics(self, outputs, labels):
        outs = _to_list(outputs)
        for m in self._metrics:
            stats = m.compute(*(outs + labels))
            m.update(*_to_list(stats))

    def _metric_logs(self, logs):
        for m in self._metrics:
            res = m.accumulate()
            names = m.name()
            if isinstance(names, (list, tuple)):
                for n, r in zip(names, _to_list(res)):
                    logs[n] = r
            else:
                logs[names] = res
        return logs

    # -- loops ---------------------------------------------------------------
    @staticmethod
    def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # any iterable of batches

    @staticmethod
    def _split_batch(batch):
        batch = _to_list(batch)
        if len(batch) < 2:
            return batch, []
        return batch[:-1], batch[-1:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert train_data is not None, "train_data must be given!"
        loader = self._as_loader(train_data, batch_size, shuffle, drop_last,
                                 num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, False,
                                      num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        metric_names = ["loss"]
        for m in self._metrics:
            metric_names.extend(_to_list(m.name()))
        cbks = config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, save_freq=save_freq,
            save_dir=save_dir, verbose=verbose, metrics=metric_names)
        self.stop_training = False
        cbks.on_train_begin()
        history = []
        total_iters = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            pending_grads = False
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                losses = self.train_batch(ins, labs, update=update)
                pending_grads = not update
                logs["loss"] = losses[0] if len(losses) == 1 else losses
                logs["batch_size"] = (_batch_len(ins[0], batch_size)
                                      if ins else batch_size)
                if self._train_step is None:
                    self._metric_logs(logs)
                cbks.on_train_batch_end(step, logs)
                total_iters += 1
                if num_iters is not None and total_iters >= num_iters:
                    self.stop_training = True
                    break
            if pending_grads:
                # flush a partial accumulation group so stale grads never
                # leak into the next epoch's first update
                self._optimizer.step()
                self._optimizer.clear_grad()
            cbks.on_epoch_end(epoch, logs)
            history.append(dict(logs))
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              log_freq=log_freq, verbose=verbose,
                              callbacks=cbks, _in_fit=True)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _in_fit=False):
        loader = self._as_loader(eval_data, batch_size, False, False,
                                 num_workers)
        cbks = callbacks if _in_fit else config_callbacks(
            callbacks, model=self, batch_size=batch_size, verbose=verbose,
            log_freq=log_freq, mode="eval")
        for m in self._metrics:
            m.reset()
        metric_names = []
        for m in self._metrics:
            metric_names.extend(_to_list(m.name()))
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks.on_eval_begin({"steps": steps,
                            "metrics": ["loss"] + metric_names})
        logs = {}
        seen = 0
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            losses = self.eval_batch(ins, labs)
            if losses:
                logs["loss"] = losses[0] if len(losses) == 1 else losses
            seen += _batch_len(ins[0], 0) if ins else 0
            self._metric_logs(logs)
            cbks.on_eval_batch_end(step, logs)
            if num_samples is not None and seen >= num_samples:
                break
        logs["samples"] = seen
        cbks.on_eval_end(logs)
        return {k: v for k, v in logs.items() if k != "samples"}

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, False,
                                 num_workers)
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                verbose=verbose, mode="predict")
        cbks.on_predict_begin()
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            ins = _to_list(batch)
            # when the Model declared input specs, only that many leading
            # elements are inputs (a test loader may still carry labels)
            if self._inputs:
                ins = ins[: len(self._inputs)]
            outs = self.predict_batch(ins)
            outputs.append(outs)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        # transpose [steps][n_out] -> [n_out][steps]
        n_out = len(outputs[0]) if outputs else 0
        cols = [[o[i] for o in outputs] for i in range(n_out)]
        if stack_outputs:
            cols = [np.concatenate(c, axis=0) for c in cols]
        return cols

    # -- persistence ----------------------------------------------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        io_save.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            io_save.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = io_save.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(io_save.load(opt_path))

    def summary(self, input_size=None, dtype=None):
        return summary_of(self.network)


def summary_of(network):
    total, trainable = 0, 0
    rows = []
    for name, p in network.named_parameters():
        n = int(np.prod(p.shape or (1,)))
        total += n
        if p.trainable:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    return {"total_params": total, "trainable_params": trainable,
            "layers": rows}


def summary(net, input_size=None, dtypes=None):
    """paddle.summary parity (prints a small table, returns the dict)."""
    info = summary_of(net)
    width = max([len(r[0]) for r in info["layers"]] + [10])
    print(f"{'Param':<{width}}  Shape            #")
    for name, shape, n in info["layers"]:
        print(f"{name:<{width}}  {str(shape):<15}  {n}")
    print(f"Total params: {info['total_params']}  "
          f"(trainable {info['trainable_params']})")
    return {"total_params": info["total_params"],
            "trainable_params": info["trainable_params"]}
