from . import models  # noqa: F401
