"""Expert-aware global-norm clip.

Rebuild of python/paddle/incubate/distributed/models/moe/grad_clip.py:§0
(ClipGradForMOEByGlobalNorm): expert parameters (tagged ``p.expert``) are
local to an expert-parallel rank, so their squared norm must be summed over
the expert group before joining the global norm. Single-controller arrays
are already global; in the manual shard_map path the psum over the expert
axis mirrors the reference's allreduce on the moe group.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .....optimizer.clip import ClipGradByGlobalNorm
from .....parallel import pcontext


def _sq_sum(grads):
    sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads
          if g is not None]
    if not sq:
        return jnp.asarray(0.0, jnp.float32)
    total = sq[0]
    for s in sq[1:]:
        total = total + s
    return total


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name)
        self.moe_group = moe_group
        self.is_expert_param_func = is_expert_param_func or (
            lambda p: getattr(p, "expert", False))

    def _clip(self, params_grads):
        normal = [(p, g) for p, g in params_grads
                  if g is not None and not self.is_expert_param_func(p)]
        expert = [(p, g) for p, g in params_grads
                  if g is not None and self.is_expert_param_func(p)]
        sq_normal = _sq_sum([g for p, g in normal
                             if getattr(p, "need_clip", True)])
        sq_expert = _sq_sum([g for p, g in expert
                             if getattr(p, "need_clip", True)])
        if pcontext.in_manual_mode():
            ax = pcontext.manual_axis("expert") or pcontext.manual_axis("ep")
            if ax is not None:
                sq_expert = lax.psum(sq_expert, ax)
        gnorm = jnp.sqrt(sq_normal + sq_expert)
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, (g.astype(jnp.float32) * scale).astype(g.dtype)))
        return out


ClipGradByGlobalNormForMOE = ClipGradForMOEByGlobalNorm
