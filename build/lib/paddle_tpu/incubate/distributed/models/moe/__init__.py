"""Mixture-of-Experts (reference:
python/paddle/incubate/distributed/models/moe/ — SURVEY.md §2.4 EP row)."""

from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401
from .grad_clip import ClipGradForMOEByGlobalNorm  # noqa: F401
from .utils import global_gather, global_scatter  # noqa: F401
