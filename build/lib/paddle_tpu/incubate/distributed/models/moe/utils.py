"""MoE comm-op parity wrappers.

Reference: python/paddle/distributed/utils/moe_utils.py:§0 exposes
``global_scatter`` / ``global_gather`` (NCCL alltoall dispatch). On TPU these
are the dispatch/combine einsums (ops.moe_ops) whose expert dim lowers to an
ICI all_to_all under an expert-sharded mesh; these wrappers keep the API.
"""

from __future__ import annotations

import jax.numpy as jnp

from .....core.tensor import Tensor
from .....ops import moe_ops


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def global_scatter(x, local_count, global_count, group=None):
    """Tokens, sorted by destination expert, are scattered into expert-major
    layout. Count-based ragged semantics are realized with capacity padding
    (static shapes): capacity = max count."""
    lc = _v(local_count).astype(jnp.int32)
    xv = _v(x)
    n_expert = lc.shape[0]
    cap = int(jnp.max(lc))
    # rebuild per-token expert ids from counts (tokens arrive expert-sorted)
    ids = jnp.repeat(jnp.arange(n_expert), lc, total_repeat_length=xv.shape[0])
    disp, _ = moe_ops.dispatch_combine_masks(ids, jnp.ones_like(ids, jnp.float32),
                                             n_expert, cap)
    return Tensor(moe_ops.moe_dispatch(xv, disp.astype(xv.dtype)))


def global_gather(x, local_count, global_count, group=None):
    """Inverse of global_scatter: expert-major (E,C,d) back to token order."""
    lc = _v(local_count).astype(jnp.int32)
    xv = _v(x)
    n_expert = lc.shape[0]
    cap = xv.shape[1] if xv.ndim == 3 else int(jnp.max(lc))
    total = int(jnp.sum(lc))
    ids = jnp.repeat(jnp.arange(n_expert), lc, total_repeat_length=total)
    disp, _ = moe_ops.dispatch_combine_masks(ids, jnp.ones((total,), jnp.float32),
                                             n_expert, cap)
    return Tensor(moe_ops.moe_combine(xv.reshape(n_expert, cap, -1),
                                      disp.astype(xv.dtype)))
