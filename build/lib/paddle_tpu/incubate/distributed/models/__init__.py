from . import moe  # noqa: F401
