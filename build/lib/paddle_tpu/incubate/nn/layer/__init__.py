from . import fused_transformer  # noqa: F401
