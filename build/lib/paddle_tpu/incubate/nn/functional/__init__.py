"""Functional surface for the fused layers (reference:
python/paddle/incubate/nn/functional/fused_transformer.py:§0)."""

from __future__ import annotations

from ....core.dispatch import apply
from ....ops import fused_transformer_block as ftb
from ....ops.rms_norm import rms_norm_array
from ....ops.fused_linear import fused_linear_param_grad_add  # noqa: F401


def fused_multi_transformer(x, params, *, num_heads, activation="gelu",
                            epsilon=1e-5, attn_mask=None, cache_kvs=None,
                            time_step=None, max_cache_len=None, seq_lens=None):
    """Tensor-level entry for the fused decoder stack
    (ops/fused_transformer_block.py). Mirrors
    paddle.incubate.nn.functional.fused_multi_transformer:§0; the layer loop
    is a scanned XLA computation rather than a CUDA megakernel."""
    tensors = [x]
    keys = sorted(params)
    tensors += [params[k] for k in keys]
    if cache_kvs is not None:
        tensors.append(cache_kvs)

    def fn(xv, *rest):
        pv = dict(zip(keys, rest[:len(keys)]))
        cache = rest[len(keys)] if cache_kvs is not None else None
        out, kv = ftb.fused_multi_transformer_array(
            xv, pv, num_heads=num_heads, act=activation, epsilon=epsilon,
            attn_mask=attn_mask, cache_kv=cache, time_step=time_step,
            max_cache_len=max_cache_len, seq_lens=seq_lens)
        return out if kv is None else (out, kv)

    return apply(fn, *tensors, op_name="fused_multi_transformer")


def fused_rms_norm(x, weight, epsilon=1e-6):
    """paddle.incubate.nn.functional.fused_rms_norm:§0 parity (Pallas kernel
    in ops/rms_norm.py)."""
    return apply(lambda xv, wv: rms_norm_array(xv, wv, epsilon), x, weight,
                 op_name="fused_rms_norm")
