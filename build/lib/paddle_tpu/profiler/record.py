"""Host span recorder + RecordEvent annotation API.

Reference: RecordEvent (python/paddle/profiler/utils.py) backed by the C++
thread-local HostEventRecorder (paddle/fluid/platform/profiler/
host_tracer.cc — SURVEY.md §5.1). Here the recorder is a process-global,
thread-aware span list; when a capture is active each span additionally
enters a ``jax.profiler.TraceAnnotation`` so it shows up in XLA xplane
traces (TensorBoard) correlated with device activity.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, NamedTuple, Optional


class HostSpan(NamedTuple):
    name: str
    event_type: str
    start_ns: int
    end_ns: int
    tid: int
    pid: int


class _HostRecorder:
    """HostEventRecorder equivalent: lock-guarded span sink, armed only
    while a Profiler capture window is active (zero overhead otherwise)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: List[HostSpan] = []
        self.enabled = False

    def emit(self, span: HostSpan) -> None:
        with self._lock:
            self._spans.append(span)

    def drain(self) -> List[HostSpan]:
        with self._lock:
            spans, self._spans = self._spans, []
        return spans

    def clear(self) -> None:
        self.drain()


host_recorder = _HostRecorder()

_MAIN_PID = threading.main_thread().ident or 0


class RecordEvent:
    """User annotation span (parity: paddle.profiler.RecordEvent).

    Usable as a context manager or via explicit begin()/end(). Event types
    mirror the reference's TracerEventType names (UserDefined, Operator,
    Dataloader, Communication, Forward, Backward, Optimization...).
    """

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._start_ns: Optional[int] = None
        self._jax_ann = None

    def begin(self) -> None:
        self._start_ns = time.perf_counter_ns()
        if host_recorder.enabled:
            try:
                import jax.profiler as jprof
                self._jax_ann = jprof.TraceAnnotation(self.name)
                self._jax_ann.__enter__()
            except Exception:
                self._jax_ann = None

    def end(self) -> None:
        if self._start_ns is None:
            return
        if self._jax_ann is not None:
            try:
                self._jax_ann.__exit__(None, None, None)
            finally:
                self._jax_ann = None
        if host_recorder.enabled:
            host_recorder.emit(HostSpan(
                self.name, self.event_type, self._start_ns,
                time.perf_counter_ns(),
                threading.get_ident(), _MAIN_PID))
        self._start_ns = None

    def __enter__(self) -> "RecordEvent":
        self.begin()
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


def record_function(name: str, event_type: str = "UserDefined"):
    """Decorator form of RecordEvent."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(name, event_type):
                return fn(*args, **kwargs)
        return wrapper

    return deco
