"""Summary tables over collected host spans.

Reference: python/paddle/profiler/profiler_statistic.py (SURVEY.md §5.1) —
aggregates spans by name into count/total/avg/max/min tables, sortable.
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional, Sequence

from .record import HostSpan


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3


_UNIT = {"s": 1e-9, "ms": 1e-6, "us": 1e-3, "ns": 1.0}


class _Agg:
    __slots__ = ("count", "total", "max", "min")

    def __init__(self):
        self.count = 0
        self.total = 0
        self.max = 0
        self.min = None

    def add(self, dur: int) -> None:
        self.count += 1
        self.total += dur
        self.max = max(self.max, dur)
        self.min = dur if self.min is None else min(self.min, dur)


def aggregate(spans: Sequence[HostSpan]) -> Dict[str, _Agg]:
    table: Dict[str, _Agg] = {}
    for sp in spans:
        table.setdefault(sp.name, _Agg()).add(sp.end_ns - sp.start_ns)
    return table


def summary(spans: Sequence[HostSpan], sorted_by: Optional[SortedKeys] = None,
            time_unit: str = "ms") -> str:
    """Render the event summary table as a string."""
    scale = _UNIT.get(time_unit, 1e-6)
    table = aggregate(spans)
    key = sorted_by or SortedKeys.CPUTotal
    sort_fn = {
        SortedKeys.CPUTotal: lambda kv: kv[1].total,
        SortedKeys.CPUAvg: lambda kv: kv[1].total / max(kv[1].count, 1),
        SortedKeys.CPUMax: lambda kv: kv[1].max,
        SortedKeys.CPUMin: lambda kv: kv[1].min or 0,
    }[key]
    rows = sorted(table.items(), key=sort_fn, reverse=True)
    name_w = max([len(n) for n, _ in rows] + [10])
    hdr = (f"{'Name':<{name_w}}  {'Calls':>7}  {'Total(' + time_unit + ')':>12}  "
           f"{'Avg(' + time_unit + ')':>12}  {'Max(' + time_unit + ')':>12}  "
           f"{'Min(' + time_unit + ')':>12}")
    lines = [hdr, "-" * len(hdr)]
    for name, agg in rows:
        lines.append(
            f"{name:<{name_w}}  {agg.count:>7}  {agg.total*scale:>12.4f}  "
            f"{agg.total*scale/max(agg.count,1):>12.4f}  "
            f"{agg.max*scale:>12.4f}  {(agg.min or 0)*scale:>12.4f}")
    return "\n".join(lines)
