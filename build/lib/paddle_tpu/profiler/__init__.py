"""``paddle_tpu.profiler`` — tracing/profiling parity surface.

Rebuild of paddle.profiler (reference: python/paddle/profiler/profiler.py,
utils.py, profiler_statistic.py; C++ host tracer
paddle/fluid/platform/profiler/host_tracer.cc — SURVEY.md §5.1). TPU-first:
device-side spans come from the XLA profiler (``jax.profiler`` xplane traces,
viewable in TensorBoard/XProf) rather than CUPTI; the framework keeps its own
host-span recorder (the HostEventRecorder equivalent) for `RecordEvent`
annotations, the executor/dataloader hooks, and chrome-tracing export.
"""

from .profiler import (  # noqa: F401
    Profiler, ProfilerState, ProfilerTarget, make_scheduler,
    export_chrome_tracing, export_protobuf,
)
from .record import RecordEvent, record_function, host_recorder  # noqa: F401
from .statistic import SortedKeys, summary  # noqa: F401
from . import statistic as profiler_statistic  # noqa: F401
