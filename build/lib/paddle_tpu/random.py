"""Framework RNG state.

Rebuild of the reference's generator/RNG plane (paddle/phi/core/generator.cc,
python/paddle — ``paddle.seed``; SURVEY.md §2.4 RNGStatesTracker row) on jax
PRNG keys. A single global key advances by fold-in counter; distributed
per-mesh-axis RNG lives in paddle_tpu.distributed.meta_parallel.random.

Under ``jit`` tracing, the compiled-step machinery (paddle_tpu.jit) installs a
*traced* key so dropout masks differ per call without retracing.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


class _RNGState:
    """Key creation is lazy: materialising a jax PRNG key initialises the
    backend, and importing the package must not dial the TPU (the launcher
    process, for one, never touches a device)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._base_key = None
        self.counter = 0
        self.traced_key = None  # set by jit machinery during trace

    @property
    def base_key(self):
        if self._base_key is None:
            self._base_key = jax.random.key(self.seed)
        return self._base_key

    @base_key.setter
    def base_key(self, key):
        self._base_key = key

    def next_key(self):
        if self.traced_key is not None:
            self.counter += 1
            return jax.random.fold_in(self.traced_key, self.counter)
        self.counter += 1
        return jax.random.fold_in(self.base_key, self.counter)


_state = _RNGState(0)


def seed(s: int) -> None:
    """Parity with ``paddle.seed``."""
    global _state
    _state = _RNGState(int(s))


def next_key():
    return _state.next_key()


def get_rng_state():
    return (_state.counter, _state.base_key)


def set_rng_state(state) -> None:
    _state.counter, _state.base_key = state


class traced_key_scope:
    """Install a traced key for the duration of a jit trace."""

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        self.prev = (_state.traced_key, _state.counter)
        _state.traced_key = self.key
        _state.counter = 0
        return self

    def __exit__(self, *exc):
        _state.traced_key, _state.counter = self.prev
        return False
