"""``nn.Layer`` — module container.

Parity with the reference's python/paddle/nn/layer/layers.py (``Layer``:
parameter/sublayer registries, hooks, state_dict — SURVEY.md §2.5 user-API
row). Parameters are ``Parameter`` tensors; the functional bridge
(paddle_tpu.jit) lifts them into pytrees for compiled training steps.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Parameter, Tensor
from . import initializer as I


class ParamAttr:
    """Parity with paddle.ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 trainable=True, regularizer=None, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.trainable = trainable
        self.regularizer = regularizer
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        raise TypeError(f"invalid param attr {attr!r}")


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: "collections.OrderedDict[int, Callable]" = collections.OrderedDict()
        self._forward_post_hooks: "collections.OrderedDict[int, Callable]" = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------------ attrs
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter) and params is not None:
            for d in (subs, buffers):
                d.pop(name, None) if d else None
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer) and subs is not None:
            subs[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params.pop(name)
                object.__setattr__(self, name, None)
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if isinstance(value, Tensor) or value is None:
                buffers[name] = value
            else:
                buffers[name] = Tensor(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # ------------------------------------------------------------- creation
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer or (
            I.Constant(0.0) if is_bias else I.XavierUniform())
        value = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------- traversal
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + "." + pname if name else pname), p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + "." + bname if name else bname), b

    def _traverse(self, prefix: str, include_sublayers: bool):
        yield prefix, self
        if include_sublayers:
            for sname, s in self._sub_layers.items():
                if s is None:
                    continue
                sub_prefix = prefix + "." + sname if prefix else sname
                yield from s._traverse(sub_prefix, True)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        out = [layer for _, layer in self._traverse("", True)]
        return out if include_self else out[1:]

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        for name, layer in self._traverse(prefix, True):
            if not include_self and layer is self:
                continue
            yield name, layer

    def children(self) -> Iterator["Layer"]:
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ------------------------------------------------------------- modes
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # ------------------------------------------------------------- state
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix, include_sublayers):
            dest[name] = p
        for name, layer in self._traverse(structured_name_prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[(name + "." + bname) if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            val = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if tuple(val.shape) != tuple(tgt._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: saved {tuple(val.shape)} vs "
                    f"current {tuple(tgt._value.shape)}")
            tgt._value = val.astype(tgt._value.dtype)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ------------------------------------------------------------- dtype/device
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._value = p._value.astype(d)
            for _, b in self.named_buffers():
                if b is not None and jnp.issubdtype(b._value.dtype, jnp.floating):
                    b._value = b._value.astype(d)
            for layer in self.sublayers(include_self=True):
                layer._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------------------------------------------- hooks/call
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + ("\n  ".join(sub_repr)))
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n" + "\n".join(lines) + "\n"
        return main + ")"


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, s in enumerate(sublayers):
                self.add_sublayer(str(i), s)

    def __len__(self):
        return len(self._sub_layers)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx % len(self))] = layer

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def insert(self, index, layer):
        items = list(self._sub_layers.values())
        items.insert(index, layer)
        self._sub_layers = collections.OrderedDict(
            (str(i), l) for i, l in enumerate(items))


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __len__(self):
        return len(self._sub_layers)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __len__(self):
        return len(self._parameters)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self
