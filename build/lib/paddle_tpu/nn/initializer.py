"""Weight initializers.

Parity with python/paddle/nn/initializer/ of the reference (SURVEY.md §2.1 op
corpus; the reference implements these as fill ops). Initializers are callables
``(shape, dtype) -> jax array`` drawing from the framework PRNG.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import random as _random


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = _random.next_key()
        return jax.random.uniform(k, shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = _random.next_key()
        return (jax.random.normal(k, shape, dtype=jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = _random.next_key()
        return (jax.random.truncated_normal(k, -2.0, 2.0, shape, dtype=jnp.float32)
                * self.std + self.mean).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = math.sqrt(6.0 / (fi + fo))
        k = _random.next_key()
        return jax.random.uniform(k, shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = math.sqrt(2.0 / (fi + fo))
        k = _random.next_key()
        return (jax.random.normal(k, shape, dtype=jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        k = _random.next_key()
        return jax.random.uniform(k, shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        k = _random.next_key()
        return (jax.random.normal(k, shape, dtype=jnp.float32) * std).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = jnp.asarray(getattr(self.value, "_value", self.value), dtype=dtype)
        assert tuple(v.shape) == tuple(shape), (v.shape, shape)
        return v


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = _random.next_key()
        return (jax.random.orthogonal(k, shape[-1], shape=shape[:-2])
                if len(shape) > 2 else
                jax.random.orthogonal(k, max(shape), shape=())[: shape[0], : shape[1]]
                ).astype(dtype) * self.gain


# calculate_gain parity
def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]
