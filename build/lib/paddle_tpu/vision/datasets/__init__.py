"""Vision datasets — parity with python/paddle/vision/datasets/:§0 (MNIST,
Cifar10/100, DatasetFolder/ImageFolder, FashionMNIST).

Offline build: constructors take local file paths (``download=True`` raises);
``FakeData`` provides a synthetic ImageNet-shaped stream for benchmarks so the
input pipeline can be exercised with zero files on disk.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io import Dataset


class FakeData(Dataset):
    """Synthetic dataset: deterministic random images + labels (benchmark
    input pipeline; not in the reference, needed for offline parity tests)."""

    def __init__(self, size=1000, image_shape=(224, 224, 3), num_classes=1000,
                 transform=None, seed=0):
        self.size = size
        # images are generated HWC (the layout every transform expects)
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.randint(0, 256, size=self.image_shape, dtype=np.uint8)
        label = rng.randint(0, self.num_classes)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32) / 255.0
        return img, np.int64(label)


class MNIST(Dataset):
    """MNIST from local idx-gzip files (reference: datasets/mnist.py:§0)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path is None or label_path is None or \
                not os.path.exists(image_path) or not os.path.exists(label_path):
            raise RuntimeError(
                "offline build: provide local image_path/label_path "
                "(download is unavailable)")
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    @staticmethod
    def _load(image_path, label_path):
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n2 = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            labels = np.frombuffer(f.read(), dtype=np.uint8)
        assert n == n2
        return images, labels

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


FashionMNIST = MNIST  # same file format; caller points at the FashionMNIST files


class Cifar10(Dataset):
    """CIFAR-10/100 from the local python-version tarball
    (reference: datasets/cifar.py:§0)."""

    _n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                "offline build: provide local data_file (download is "
                "unavailable)")
        self.mode = mode
        self.transform = transform
        self.data, self.labels = self._load(data_file, mode)

    def _load(self, data_file, mode):
        datas, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = [m for m in tf.getmembers()
                     if (("data_batch" in m.name or "train" in m.name)
                         if mode == "train"
                         else ("test" in m.name))]
            for m in sorted(names, key=lambda m: m.name):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                if b"data" not in d:
                    continue
                datas.append(np.asarray(d[b"data"]))
                key = b"labels" if b"labels" in d else b"fine_labels"
                labels.extend(d[key])
        data = np.concatenate(datas).reshape(-1, 3, 32, 32)
        return data, np.asarray(labels, dtype=np.int64)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar100(Cifar10):
    _n_classes = 100


_DEFAULT_EXTENSIONS = (".npy",)


def _default_loader(path):
    return np.load(path)


def _iter_valid_files(dirpath, fnames, extensions, is_valid_file):
    for fname in sorted(fnames):
        path = os.path.join(dirpath, fname)
        ok = (is_valid_file(path) if is_valid_file is not None
              else fname.lower().endswith(extensions))
        if ok:
            yield path


class DatasetFolder(Dataset):
    """class-per-subdir image folder (reference: datasets/folder.py:§0).
    ``loader`` defaults to raw-numpy .npy loading; image decoding is
    caller-provided (no PIL/cv2 dependency in this build)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        extensions = extensions or _DEFAULT_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for path in _iter_valid_files(cdir, os.listdir(cdir), extensions,
                                          is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        self.transform = transform

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)


class ImageFolder(DatasetFolder):
    """Flat / recursive folder of images without labels."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        extensions = extensions or _DEFAULT_EXTENSIONS
        self.samples = []
        for dirpath, _, fnames in sorted(os.walk(root)):
            self.samples.extend(
                _iter_valid_files(dirpath, fnames, extensions, is_valid_file))
        self.transform = transform

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return (img,)
