"""VGG family — parity with python/paddle/vision/models/vgg.py:§0."""

from __future__ import annotations

from ... import nn

_cfgs = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512,
          512, "M", 512, 512, 512, 512, "M"],
}


def _make_features(cfg, batch_norm=False):
    layers = []
    in_channels = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(kernel_size=2, stride=2))
        else:
            layers.append(nn.Conv2D(in_channels, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_channels = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096),
                nn.ReLU(),
                nn.Dropout(),
                nn.Linear(4096, 4096),
                nn.ReLU(),
                nn.Dropout(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _vgg(arch, pretrained=False, batch_norm=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled (offline build)")
    return VGG(_make_features(_cfgs[arch], batch_norm=batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", pretrained, batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", pretrained, batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", pretrained, batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", pretrained, batch_norm, **kwargs)
