"""ResNet family — workload #1 of the baseline.

Parity with the reference's python/paddle/vision/models/resnet.py:§0
(BasicBlock / BottleneckBlock / ResNet, constructors resnet18..152, wide and
resnext variants). TPU notes: the whole model is plain traced jax — XLA tiles
the convs onto the MXU; BatchNorm folds into the conv epilogue under jit.
"""

from __future__ import annotations

from ... import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        if norm_layer is None:
            norm_layer = nn.BatchNorm2D
        if dilation > 1:
            raise NotImplementedError("dilation > 1 not supported in BasicBlock")
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        if norm_layer is None:
            norm_layer = nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=dilation,
                               groups=groups, dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ResNet backbone. ``depth`` selects the standard configs; ``width`` scales
    the bottleneck width (wide variants); ``groups`` gives ResNeXt."""

    _cfg = {
        18: (BasicBlock, [2, 2, 2, 2]),
        34: (BasicBlock, [3, 4, 6, 3]),
        50: (BottleneckBlock, [3, 4, 6, 3]),
        101: (BottleneckBlock, [3, 4, 23, 3]),
        152: (BottleneckBlock, [3, 8, 36, 3]),
    }

    def __init__(self, block=None, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        if block is None:
            block, layer_cfg = self._cfg[depth]
        else:
            layer_cfg = self._cfg[depth][1]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.groups = groups
        self.base_width = width
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layer_cfg[0])
        self.layer2 = self._make_layer(block, 128, layer_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layer_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layer_cfg[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, self.dilation)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _resnet(depth, pretrained=False, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled (offline build)")
    return ResNet(depth=depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return _resnet(50, pretrained, width=128, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return _resnet(101, pretrained, width=128, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return _resnet(50, pretrained, groups=32, width=4, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnet(101, pretrained, groups=64, width=4, **kwargs)
