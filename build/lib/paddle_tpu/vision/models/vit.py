"""Vision Transformer (ViT) on the fused attention/FFN blocks.

Workload #5's transformer-vision surface (SURVEY.md §6: ViT-L is one of
the five benchmark configs). Pre-LN encoder built from the same fused
incubate blocks as the language models — patch embedding is a strided
Conv2D (one MXU matmul per patch grid), class token + learned positions,
mean/cls pooling head. Reference surface: the model-zoo
VisionTransformer family.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ...incubate.nn.layer.fused_transformer import (
    FusedFeedForward, FusedMultiHeadAttention)
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.common_layers import Conv2D, LayerNorm, Linear
from ...nn.layer import Layer, LayerList


class PatchEmbed(Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768):
        super().__init__()
        if img_size % patch_size:
            raise ValueError("img_size must divide by patch_size")
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = Conv2D(in_chans, embed_dim, kernel_size=patch_size,
                           stride=patch_size)

    def forward(self, x):
        x = self.proj(x)                       # (B, E, H/p, W/p)
        b, e = x.shape[0], x.shape[1]
        return x.reshape([b, e, -1]).transpose([0, 2, 1])  # (B, N, E)


class ViTEncoderLayer(Layer):
    def __init__(self, embed_dim, num_heads, mlp_ratio=4.0, epsilon=1e-6):
        super().__init__()
        self.attn = FusedMultiHeadAttention(
            embed_dim, num_heads, normalize_before=True, epsilon=epsilon)
        self.ffn = FusedFeedForward(
            embed_dim, int(embed_dim * mlp_ratio), activation="gelu",
            normalize_before=True, epsilon=epsilon)

    def forward(self, x):
        return self.ffn(self.attn(x, causal=False))


class VisionTransformer(Layer):
    """ViT backbone + classification head (class_num=0 → features only)."""

    def __init__(self, img_size=224, patch_size=16, in_chans=3,
                 class_num=1000, embed_dim=768, depth=12, num_heads=12,
                 mlp_ratio=4.0, epsilon=1e-6, representation_size=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans,
                                      embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = self.create_parameter(
            (1, 1, embed_dim), default_initializer=I.Normal(0.0, 0.02))
        self.pos_embed = self.create_parameter(
            (1, n + 1, embed_dim), default_initializer=I.Normal(0.0, 0.02))
        self.blocks = LayerList([
            ViTEncoderLayer(embed_dim, num_heads, mlp_ratio, epsilon)
            for _ in range(depth)])
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.head = (Linear(embed_dim, class_num) if class_num > 0 else None)

    def forward_features(self, x):
        from ...core.dispatch import apply
        x = self.patch_embed(x)

        def add_tokens(xv, cls, pos):
            b = xv.shape[0]
            cls_b = jnp.broadcast_to(cls, (b,) + cls.shape[1:])
            return jnp.concatenate([cls_b, xv], axis=1) + pos

        x = apply(add_tokens, x, self.cls_token, self.pos_embed,
                  op_name="vit_tokens")
        for blk in self.blocks:
            x = blk(x)
        return self.norm(x)

    def forward(self, x):
        feats = self.forward_features(x)
        cls = feats[:, 0]
        return self.head(cls) if self.head is not None else cls


def vit_base_patch16_224(**kwargs):
    return VisionTransformer(img_size=224, patch_size=16, embed_dim=768,
                             depth=12, num_heads=12, **kwargs)


def vit_large_patch16_224(**kwargs):
    return VisionTransformer(img_size=224, patch_size=16, embed_dim=1024,
                             depth=24, num_heads=16, **kwargs)


def vit_tiny_test(**kwargs):
    """Small config for tests/CI."""
    base = dict(img_size=16, patch_size=4, in_chans=3, class_num=10,
                embed_dim=32, depth=2, num_heads=4)
    base.update(kwargs)
    return VisionTransformer(**base)
