"""MobileNet V1/V2 — parity with python/paddle/vision/models/mobilenetv1.py:§0
and mobilenetv2.py:§0. Depthwise convs go through grouped conv2d (XLA lowers
groups==channels to depthwise on TPU)."""

from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1, relu6=False):
        padding = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6() if relu6 else nn.ReLU(),
        )


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = _ConvBNReLU(in_c, in_c, 3, stride=stride, groups=in_c)
        self.pw = _ConvBNReLU(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return int(ch * scale)

        cfg = [  # (out, stride)
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
            (1024, 2), (1024, 1),
        ]
        layers = [_ConvBNReLU(3, c(32), 3, stride=2)]
        in_c = c(32)
        for out, stride in cfg:
            layers.append(_DepthwiseSeparable(in_c, c(out), stride))
            in_c = c(out)
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1, relu6=True))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden, relu6=True),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, relu6=True)]
        for t, c_, n, s in cfg:
            out_c = _make_divisible(c_ * scale)
            for i in range(n):
                layers.append(InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU(in_c, last_c, 1, relu6=True))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled (offline build)")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise ValueError("pretrained weights are not bundled (offline build)")
    return MobileNetV2(scale=scale, **kwargs)
