"""Image transforms — parity with python/paddle/vision/transforms/:§0
(transforms.py class surface + functional.py).

Host-side numpy pipeline: transforms run in DataLoader workers on CPU; only
the final batched array crosses to the TPU (SURVEY.md §2.5 DataLoader row —
keep host↔device transfers to one per batch).
"""

from __future__ import annotations

import numbers
import random as _pyrandom

import numpy as np

from . import functional as F  # noqa: F401
from .functional import (  # noqa: F401
    to_tensor, resize, center_crop, crop, hflip, vflip, normalize, pad,
    adjust_brightness, adjust_contrast, rotate, to_grayscale,
)


class BaseTransform:
    """Transform base (reference: BaseTransform in transforms.py:§0).
    Subclasses implement ``_apply_image``."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        if self.keys is None:
            return self._apply_image(inputs)
        if not isinstance(inputs, (list, tuple)):
            inputs = (inputs,)
        outputs = []
        for key, data in zip(self.keys, inputs):
            if key == "image":
                outputs.append(self._apply_image(data))
            else:
                outputs.append(data)
        return tuple(outputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        if self.padding is not None:
            img = pad(img, self.padding)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (max(0, tw - w), max(0, th - h)))
            h, w = img.shape[:2]
        if h == th and w == tw:
            return img
        top = _pyrandom.randint(0, h - th)
        left = _pyrandom.randint(0, w - tw)
        return crop(img, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if _pyrandom.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if _pyrandom.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * _pyrandom.uniform(*self.scale)
            aspect = np.exp(_pyrandom.uniform(np.log(self.ratio[0]),
                                              np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = _pyrandom.randint(0, h - ch)
                left = _pyrandom.randint(0, w - cw)
                img2 = crop(img, top, left, ch, cw)
                return resize(img2, self.size, self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size, self.interpolation)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = mean
        self.std = std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        if self.to_rgb:
            img = np.asarray(img)
            # channel axis position follows data_format (reference reverses
            # BGR→RGB before normalizing)
            img = img[::-1] if self.data_format == "CHW" else img[..., ::-1]
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = _pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, factor)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def _apply_image(self, img):
        angle = _pyrandom.uniform(*self.degrees)
        return rotate(img, angle)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)
