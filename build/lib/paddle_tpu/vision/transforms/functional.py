"""Functional transforms over numpy HWC uint8/float arrays — parity with
python/paddle/vision/transforms/functional.py:§0 (cv2/PIL backends replaced by
pure numpy so the pipeline has no image-library dependency)."""

from __future__ import annotations

import numbers

import numpy as np


def _as_hwc(img) -> np.ndarray:
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(img, data_format="CHW"):
    """HWC uint8 [0,255] → float32 [0,1], optionally CHW."""
    img = _as_hwc(img)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    else:
        img = img.astype(np.float32)
    if data_format.upper() == "CHW":
        img = img.transpose(2, 0, 1)
    return img


def resize(img, size, interpolation="bilinear"):
    """Bilinear / nearest resize via vectorised numpy gather."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        # short-side resize, preserving aspect ratio (paddle semantics)
        if h <= w:
            oh, ow = int(size), max(1, int(size * w / h))
        else:
            oh, ow = max(1, int(size * h / w)), int(size)
    else:
        oh, ow = int(size[0]), int(size[1])
    if (oh, ow) == (h, w):
        return img
    in_dtype = img.dtype
    if interpolation == "nearest":
        rows = np.clip((np.arange(oh) + 0.5) * h / oh, 0, h - 1).astype(np.int64)
        cols = np.clip((np.arange(ow) + 0.5) * w / ow, 0, w - 1).astype(np.int64)
        return img[rows[:, None], cols[None, :]]
    # bilinear with half-pixel centres
    fr = np.clip((np.arange(oh) + 0.5) * h / oh - 0.5, 0, h - 1)
    fc = np.clip((np.arange(ow) + 0.5) * w / ow - 0.5, 0, w - 1)
    r0 = np.floor(fr).astype(np.int64)
    c0 = np.floor(fc).astype(np.int64)
    r1 = np.minimum(r0 + 1, h - 1)
    c1 = np.minimum(c0 + 1, w - 1)
    wr = (fr - r0)[:, None, None]
    wc = (fc - c0)[None, :, None]
    img_f = img.astype(np.float32)
    top = img_f[r0[:, None], c0[None, :]] * (1 - wc) + img_f[r0[:, None], c1[None, :]] * wc
    bot = img_f[r1[:, None], c0[None, :]] * (1 - wc) + img_f[r1[:, None], c1[None, :]] * wc
    out = top * (1 - wr) + bot * wr
    if np.issubdtype(in_dtype, np.integer):
        out = np.clip(np.round(out), 0, np.iinfo(in_dtype).max).astype(in_dtype)
    return out


def crop(img, top, left, height, width):
    img = _as_hwc(img)
    return img[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(img, top, left, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl_, pt, pr, pb = (int(padding),) * 4
    elif len(padding) == 2:
        pl_, pt = int(padding[0]), int(padding[1])
        pr, pb = pl_, pt
    else:
        pl_, pt, pr, pb = (int(p) for p in padding)
    pads = ((pt, pb), (pl_, pr), (0, 0))
    if padding_mode == "constant":
        return np.pad(img, pads, mode="constant", constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, pads, mode=mode)


def normalize(img, mean, std, data_format="CHW"):
    img = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format.upper() == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (img - mean.reshape(shape)) / std.reshape(shape)


def adjust_brightness(img, factor):
    img = _as_hwc(img)
    in_dtype = img.dtype
    out = img.astype(np.float32) * factor
    if np.issubdtype(in_dtype, np.integer):
        return np.clip(out, 0, np.iinfo(in_dtype).max).astype(in_dtype)
    return out


def adjust_contrast(img, factor):
    img = _as_hwc(img)
    in_dtype = img.dtype
    img_f = img.astype(np.float32)
    mean = img_f.mean()
    out = (img_f - mean) * factor + mean
    if np.issubdtype(in_dtype, np.integer):
        return np.clip(out, 0, np.iinfo(in_dtype).max).astype(in_dtype)
    return out


def rotate(img, angle, interpolation="nearest", fill=0):
    """Rotate about the image centre (inverse-map nearest sampling)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    rad = -np.deg2rad(angle)  # inverse transform
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ys = (yy - cy) * np.cos(rad) - (xx - cx) * np.sin(rad) + cy
    xs = (yy - cy) * np.sin(rad) + (xx - cx) * np.cos(rad) + cx
    yi = np.round(ys).astype(np.int64)
    xi = np.round(xs).astype(np.int64)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full_like(img, fill)
    out[valid] = img[yi[valid], xi[valid]]
    return out


def to_grayscale(img, num_output_channels=1):
    img = _as_hwc(img)
    in_dtype = img.dtype
    if img.shape[2] == 1:
        gray = img.astype(np.float32)
    else:
        weights = np.array([0.299, 0.587, 0.114], dtype=np.float32)
        gray = (img[..., :3].astype(np.float32) @ weights)[..., None]
    gray = np.repeat(gray, num_output_channels, axis=2)
    if np.issubdtype(in_dtype, np.integer):
        return np.clip(np.round(gray), 0, np.iinfo(in_dtype).max).astype(in_dtype)
    return gray
