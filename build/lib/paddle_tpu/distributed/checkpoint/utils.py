"""Checkpoint helpers (reference:
python/paddle/distributed/checkpoint/utils.py:§0)."""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ...core.tensor import Tensor


def flatten_state_dict(state_dict: Dict) -> Tuple[Dict[str, Any], Dict[str, Tuple[str, ...]]]:
    """Flatten a nested state dict into {joined_key: value}; returns the flat
    dict and the mapping flat_key -> original key path."""
    flat: Dict[str, Any] = {}
    mapping: Dict[str, Tuple[str, ...]] = {}

    def rec(prefix: Tuple[str, ...], d):
        if isinstance(d, dict):
            for k, v in d.items():
                rec(prefix + (str(k),), v)
        else:
            key = ".".join(prefix)
            if key in flat:
                raise ValueError(f"duplicate flat key {key!r}")
            flat[key] = d
            mapping[key] = prefix
    rec((), state_dict)
    return flat, mapping


def unflatten_state_dict(flat: Dict[str, Any],
                         mapping: Dict[str, Tuple[str, ...]]) -> Dict:
    out: Dict = {}
    for key, value in flat.items():
        path = mapping.get(key, (key,))
        d = out
        for p in path[:-1]:
            d = d.setdefault(p, {})
        d[path[-1]] = value
    return out


def to_array(value):
    """numpy view of a Tensor / jax array / scalar (bf16-safe)."""
    if isinstance(value, Tensor):
        value = value._value
    return np.asarray(value)


def offsets_from_index(index, shape) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """(global_offset, local_shape) from a jax shard ``index`` (tuple of
    slices over the global shape)."""
    if not shape:
        return (), ()
    offs, lshape = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offs.append(start)
        lshape.append(stop - start)
    return tuple(offs), tuple(lshape)
