"""``paddle.distributed.spawn`` parity: single-node multiprocess launcher.

Reference: python/paddle/distributed/spawn.py (SURVEY.md §2.6). Spawns
``nprocs`` Python processes running ``func(*args)`` with the same
``PADDLE_*`` env the launch CLI would inject, then joins them.

TPU note: one jax process owns all local chips, so per-chip spawning is a
CPU-backend testing pattern here (set JAX_PLATFORMS=cpu in the parent, or
pass ``env={...}``); on real multi-host TPU use the launch CLI per host.
"""

from __future__ import annotations

import multiprocessing as mp
import os
from typing import Dict, Optional, Sequence

from .launch.context import free_ports, free_port
from .launch.job import build_trainer_env


class ProcessContext:
    def __init__(self, procs):
        self.processes = procs

    def join(self, timeout: Optional[float] = None) -> bool:
        """Join all workers. If any worker dies non-zero while siblings are
        still running, the survivors are terminated (they may be blocked on
        a rendezvous with the dead rank) and RuntimeError is raised —
        reference spawn behaviour."""
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            alive = [p for p in self.processes if p.is_alive()]
            bad = [p for p in self.processes
                   if not p.is_alive() and p.exitcode != 0]
            if bad:
                for p in alive:
                    p.terminate()
                for p in alive:
                    p.join(5)
                raise RuntimeError(
                    f"{len(bad)} spawned process(es) failed with exit codes "
                    f"{[p.exitcode for p in bad]}")
            if not alive:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            alive[0].join(0.2)


def _worker(func, i: int, args, env: Dict[str, str]):
    os.environ.update(env)
    func(*args)


def spawn(func, args: Sequence = (), nprocs: int = 1, join: bool = True,
          daemon: bool = False, env: Optional[Dict[str, str]] = None,
          **options) -> ProcessContext:
    ports = free_ports(nprocs)
    eps = [f"127.0.0.1:{p}" for p in ports]
    master = f"127.0.0.1:{free_port()}"
    ctx = mp.get_context(options.get("start_method", "spawn"))
    procs = []
    for i in range(nprocs):
        child_env = build_trainer_env(i, nprocs, i, nprocs, eps[i], eps,
                                      master)
        if env:
            child_env.update(env)
        p = ctx.Process(target=_worker, args=(func, i, tuple(args), child_env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    pc = ProcessContext(procs)
    if join:
        pc.join()
    return pc
