from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, mark_as_sequence_parallel_parameter,
    mp_all_gather_last_dim, mp_all_reduce,
)
from .random_state import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer  # noqa: F401
from .pipeline_parallel import PipelineParallel, PipelineParallelWithInterleave  # noqa: F401
from .parallel_wrapper import HybridParallelModel  # noqa: F401
