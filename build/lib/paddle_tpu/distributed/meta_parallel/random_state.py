"""Per-group RNG state tracker.

Rebuild of python/paddle/distributed/fleet/meta_parallel/parallel_layers/
random.py (``RNGStatesTracker`` — SURVEY.md §2.4 TP row). The reference keeps
separate CUDA RNG states per parallel group so dropout inside TP regions is
identical across mp ranks ("local_seed" vs "global_seed"). With jax PRNG keys
this is fold_in bookkeeping: each named state is a key derived from the base
seed; inside shard_map, model-parallel regions additionally fold in the mp
axis index (or deliberately do NOT, to keep dropout identical across mp).
"""

from __future__ import annotations

import contextlib
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from ...parallel import pcontext

MODEL_PARALLEL_RNG = "model_parallel_rng"


def _stable_hash(name: str) -> int:
    h = 0
    for c in name:
        h = (h * 131 + ord(c)) % (2 ** 31 - 1)
    return h


class RNGStatesTracker:
    def __init__(self):
        self.states: Dict[str, jax.Array] = {}
        self.seeds = set()
        self._counters: Dict[str, int] = {}

    def reset(self):
        self.states = {}
        self.seeds = set()
        self._counters = {}

    def add(self, name: str, seed: int):
        if seed in self.seeds:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states:
            raise ValueError(f"state {name} already exists")
        self.seeds.add(seed)
        self.states[name] = jax.random.key(seed)
        self._counters[name] = 0

    def get_states_tracker(self):
        return dict(self.states), dict(self._counters)

    def set_states_tracker(self, states):
        self.states, self._counters = dict(states[0]), dict(states[1])

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        """Inside this context, framework RNG draws come from the named
        per-group state. In manual mode the key is folded with the mp axis
        index so dropout differs per mp rank (the reference's local_seed
        semantics)."""
        if name not in self.states:
            # lazily seed from the global framework seed
            self.add(name, 2718 + len(self.states))
        from ... import random as _random

        self._counters[name] += 1
        # Under a compiled step, derive from the ambient *traced* key so masks
        # vary per executed step (a concrete state key would be baked into the
        # trace and replay the same mask forever).
        ambient = _random._state.traced_key
        base = ambient if ambient is not None else self.states[name]
        key = jax.random.fold_in(base, self._counters[name])
        key = jax.random.fold_in(key, _stable_hash(name))
        ax = pcontext.manual_axis("mp")
        if pcontext.in_manual_mode() and ax is not None:
            key = jax.random.fold_in(key, lax.axis_index(ax))
        with _random.traced_key_scope(key):
            yield


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed: int = 2718):
    _tracker.reset()
    _tracker.add(MODEL_PARALLEL_RNG, seed)
