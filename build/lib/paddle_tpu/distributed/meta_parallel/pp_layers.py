"""Pipeline-layer description & partitioning.

Rebuild of python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py (LayerDesc / SharedLayerDesc / PipelineLayer — SURVEY.md §2.4 PP
row). PipelineLayer partitions a layer list into stages; execution happens in
the compiled hybrid engine (parallel/pipeline.py) rather than per-process
NCCL p2p.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ...nn.layer import Layer, LayerList, Sequential
from ..topology import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects an nn.Layer subclass")

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in multiple stages (e.g. embedding +
    output head). All instances share the first-built layer's parameters."""

    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Holds the full layer list + the stage partition.

    In the reference each process builds only its stage; in the
    single-controller rebuild all stages are built (device memory is governed
    by shardings, not host construction) and the hybrid engine maps stage
    parameters onto pp submeshes.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method="uniform",
                 recompute_interval=0, **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        hcg = get_hybrid_communicate_group()
        if num_stages is None and hcg is not None:
            num_stages = hcg.get_pipe_parallel_world_size()
        self._num_stages = max(int(num_stages or 1), 1)

        self._descs = list(layers)
        self._shared_instances = {}
        built: List[Any] = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared_instances:
                    src = self._shared_instances[d.layer_name]
                    inst = d.build_layer()
                    # tie: point the shared attr at the original Parameter
                    setattr(inst, d.shared_weight_attr,
                            getattr(src, d.shared_weight_attr))
                else:
                    inst = d.build_layer()
                    self._shared_instances[d.layer_name] = inst
                inst._pp_forward_func = d.forward_func
                built.append(inst)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(d)
            else:
                raise TypeError(f"invalid pipeline entry {d!r}")
        self.run_function = built
        self._layers_holder = LayerList([l for l in built if isinstance(l, Layer)])
        self._stage_bounds = self._partition(len(built), self._num_stages,
                                             seg_method)

    @staticmethod
    def _partition(n_layers: int, n_stages: int, seg_method: str) -> List[int]:
        """Uniform split bounds (len n_stages+1), parity with seg_method
        'uniform' / 'layer:<cls>' (uniform here)."""
        base = n_layers // n_stages
        extra = n_layers % n_stages
        bounds = [0]
        for s in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if s < extra else 0))
        return bounds

    def get_stage_layers(self, stage_id: int) -> List[Any]:
        lo, hi = self._stage_bounds[stage_id], self._stage_bounds[stage_id + 1]
        return self.run_function[lo:hi]

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def forward(self, x):
        for fn in self.run_function:
            x = fn(x) if not isinstance(x, tuple) else fn(*x)
        return x
