"""Model wrappers returned by fleet.distributed_model.

Rebuild of the reference's TensorParallel / ShardingParallel wrappers
(python/paddle/distributed/fleet/meta_parallel/{tensor_parallel,
sharding_parallel}.py — SURVEY.md §2.4). Forward stays imperative; the
compiled path is obtained with ``compile_train_step`` which returns the
GSPMD HybridTrainStep over the fleet mesh.
"""

from __future__ import annotations

from typing import Callable, Optional

from ...nn.layer import Layer


class HybridParallelModel(Layer):
    def __init__(self, model: Layer, hcg, strategy):
        super().__init__()
        self._layers = model
        self._hcg = hcg
        self._strategy = strategy
        self._train_step = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    @property
    def inner_model(self):
        return self._layers

    def compile_train_step(self, loss_fn: Callable, optimizer):
        """loss_fn(model, *batch) -> scalar. Returns the compiled hybrid step
        (cached). Strategy amp wraps the loss in auto_cast inside the traced
        program (the compiled analog of the reference's amp pass)."""
        from ..fleet.hybrid_engine import HybridTrainStep
        from ..fleet.meta_optimizers import unwrap_optimizer
        if self._train_step is None:
            if self._strategy is not None and (
                    getattr(self._strategy, "gradient_merge", False)
                    or getattr(self._strategy, "localsgd", False)):
                # these compose as eager step-loop wrappers; unwrapping to the
                # base update rule here would silently drop them
                raise ValueError(
                    "strategy.gradient_merge / strategy.localsgd are eager "
                    "step-loop transforms and are not applied inside the "
                    "compiled hybrid step — drive training through "
                    "opt.step()/clear_grad() (or use micro-batching via the "
                    "pipeline engine's accumulate_steps) instead")
            inner_opt = unwrap_optimizer(optimizer)
            stage = 1
            if self._strategy is not None and self._strategy.sharding:
                stage = int(self._strategy.sharding_configs.get("stage", 1))
            if self._strategy is not None and self._strategy.amp:
                from ... import amp as _amp
                c = self._strategy.amp_configs
                base_loss = loss_fn

                def loss_fn(model, *batch, _base=base_loss, _c=c):
                    with _amp.auto_cast(
                            enable=True, level=_c.get("level", "O1"),
                            dtype=_c.get("dtype", "bfloat16"),
                            custom_white_list=_c.get("custom_white_list"),
                            custom_black_list=_c.get("custom_black_list")):
                        return _base(model, *batch)
            self._train_step = HybridTrainStep(
                self._layers, loss_fn, inner_opt,
                mesh=self._hcg.mesh if self._hcg else None,
                zero_stage=stage)
        return self._train_step

    def train_batch(self, batch, optimizer, lr_scheduler=None, loss_fn=None):
        if self._train_step is None:
            if loss_fn is None:
                raise ValueError("first train_batch call needs loss_fn")
            self.compile_train_step(loss_fn, optimizer)
        loss = self._train_step(*batch)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss
