"""``paddle.distributed.communication.stream`` parity namespace.

Reference: python/paddle/distributed/communication/stream/*.py — collective
variants with ``use_calc_stream`` control over the NCCL comm stream
(SURVEY.md §2.3). On TPU there is no user-visible stream: XLA's async collectives and
latency-hiding scheduler play that role, so these delegate to the eager
collectives; ``use_calc_stream`` / ``sync_op`` are accepted for parity and
ignored.
"""

from __future__ import annotations

from .. import collective as _c
from .p2p import send as _send, recv as _recv


def all_reduce(tensor, op=_c.ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    return _c.all_reduce(tensor, op=op, group=group)


def all_gather(tensor_or_tensor_list, tensor=None, group=None, sync_op=True,
               use_calc_stream=False):
    return _c.all_gather(tensor_or_tensor_list, tensor, group=group)


def reduce_scatter(tensor, tensor_or_tensor_list=None, op=_c.ReduceOp.SUM,
                   group=None, sync_op=True, use_calc_stream=False):
    return _c.reduce_scatter(tensor, tensor_or_tensor_list, op=op, group=group)


def broadcast(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _c.broadcast(tensor, src=src, group=group)


def reduce(tensor, dst=0, op=_c.ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    return _c.reduce(tensor, dst=dst, op=op, group=group)


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    return _c.scatter(tensor, tensor_or_tensor_list, src=src, group=group)


def alltoall(out_tensor_or_list, in_tensor_or_list=None, group=None,
             sync_op=True, use_calc_stream=False):
    return _c.alltoall(out_tensor_or_list, in_tensor_or_list, group=group)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    return _send(tensor, dst=dst, group=group)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _recv(tensor, src=src, group=group)
