"""Point-to-point communication: send/recv, isend/irecv, batch_isend_irecv.

Reference: python/paddle/distributed/communication/{send,recv,
batch_isend_irecv}.py over ProcessGroupNCCL::Send/Recv with batched
GroupStart/End (SURVEY.md §2.3, §3.2 pipeline p2p).

TPU-first: *in-graph* p2p is ``lax.ppermute`` over a mesh axis — that is
what the pipeline engine uses on the hot path (paddle_tpu/parallel/
pipeline.py), and what a batch of matched isend/irecv pairs lowers to here
(one compiled ppermute per batch). *Eager* p2p in the single-controller
model is a host-mediated exchange: the sender parks the array in a mailbox
keyed by (src, dst, tag), the receiver copies it out — the TCPStore-era
"separate comm stream" has no analog because XLA owns scheduling.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from ...core.tensor import Tensor
from ..collective import Group, get_group, _unwrap


class _Mailbox:
    """Host-side rendezvous for eager send/recv within one controller."""

    def __init__(self):
        self._lock = threading.Condition()
        self._slots: Dict[Tuple[int, int, int], object] = {}

    def put(self, key, value, timeout: float = 60.0):
        with self._lock:
            if key in self._slots and not self._lock.wait_for(
                    lambda: key not in self._slots, timeout):
                raise TimeoutError(f"send slot {key} still occupied")
            self._slots[key] = value
            self._lock.notify_all()

    def take(self, key, timeout: float = 60.0):
        with self._lock:
            if not self._lock.wait_for(lambda: key in self._slots, timeout):
                raise TimeoutError(f"recv: nothing sent for {key}")
            val = self._slots.pop(key)
            self._lock.notify_all()
            return val


_mailbox = _Mailbox()


class P2PTask:
    """Completed-on-creation task handle (parity with ProcessGroup::Task).

    Eager exchanges resolve synchronously under the single controller, so
    ``wait()`` is trivially satisfied; ``tensor`` carries the received value
    for irecv tasks.
    """

    def __init__(self, tensor: Optional[Tensor] = None):
        self.tensor = tensor

    def wait(self) -> bool:
        return True

    def is_completed(self) -> bool:
        return True


def _check_single_process(what: str) -> None:
    """Eager p2p rendezvouses through an in-process mailbox; across OS
    processes (launch CLI / spawn, each with its own mailbox) it would hang
    until timeout. Fail fast with a pointer at the in-graph path instead."""
    from .. import env

    if env.get_world_size() > 1:
        raise RuntimeError(
            f"eager {what} is single-process only (the mailbox does not "
            "cross process boundaries). In multi-process launches use "
            "in-graph p2p: lax.ppermute over a mesh axis / "
            "batch_isend_irecv with matched pairs / the pipeline engine.")


def send(tensor, dst: int = 0, group: Optional[Group] = None,
         sync_op: bool = True, tag: int = 0):
    from ..collective import get_rank
    _check_single_process("send")
    _mailbox.put((get_rank(), dst, tag), _unwrap(tensor))
    return P2PTask()


def recv(tensor, src: int = 0, group: Optional[Group] = None,
         sync_op: bool = True, tag: int = 0):
    from ..collective import get_rank
    _check_single_process("recv")
    val = _mailbox.take((src, get_rank(), tag))
    if isinstance(tensor, Tensor):
        tensor._value = jax.numpy.asarray(val).reshape(tensor._value.shape) \
            .astype(tensor._value.dtype)
        return P2PTask(tensor)
    return P2PTask(Tensor(val))


def isend(tensor, dst: int = 0, group: Optional[Group] = None, tag: int = 0):
    return send(tensor, dst, group, sync_op=False, tag=tag)


def irecv(tensor, src: int = 0, group: Optional[Group] = None, tag: int = 0):
    return recv(tensor, src, group, sync_op=False, tag=tag)


class P2POp:
    """One batched p2p operation (parity: paddle.distributed.P2POp).

    ``op`` is the isend/irecv function; ``peer`` the remote rank.
    """

    def __init__(self, op, tensor, peer: int, group: Optional[Group] = None,
                 tag: int = 0):
        if op not in (isend, irecv):
            raise ValueError("op must be paddle_tpu.distributed.isend/irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group
        self.tag = tag


def batch_isend_irecv(p2p_op_list: List[P2POp]) -> List[P2PTask]:
    """Execute a batch of matched isend/irecv pairs.

    When every send has a matching receive *within the batch* (the pipeline
    pattern: reference GroupStart/End), the batch lowers to ONE compiled
    ``lax.ppermute`` over the group's mesh axis — the ICI-native form.
    Unmatched ops fall back to the eager mailbox exchange.
    """
    if not p2p_op_list:
        return []
    # Sends post first so the receives in the same batch can't deadlock —
    # the GroupStart/End ordering guarantee of the reference.
    tasks: List[P2PTask] = []
    for op in p2p_op_list:
        if op.op is isend:
            tasks.append(send(op.tensor, op.peer, op.group, tag=op.tag))
    for op in p2p_op_list:
        if op.op is irecv:
            tasks.append(recv(op.tensor, op.peer, op.group, tag=op.tag))
    return tasks


def ppermute_exchange(x, axis: str, perm: List[Tuple[int, int]]):
    """In-graph batched p2p: the compiled path used by pipeline schedules.
    Call inside shard_map; ``perm`` is [(src, dst), ...] as in lax.ppermute."""
    return lax.ppermute(x, axis, perm)
