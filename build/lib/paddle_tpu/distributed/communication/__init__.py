"""``paddle_tpu.distributed.communication`` — functional collective API.

Parity with python/paddle/distributed/communication/ (SURVEY.md §2.3 Python
comm API row): re-exports the collective functions plus point-to-point ops
and the ``stream`` namespace.
"""

from ..collective import (  # noqa: F401
    ReduceOp, Group, new_group, all_reduce, all_gather, reduce_scatter,
    broadcast, reduce, scatter, alltoall, all_to_all, barrier,
    get_world_size, get_rank,
)
from .p2p import (  # noqa: F401
    P2POp, batch_isend_irecv, isend, irecv, send, recv, P2PTask,
)
from . import stream  # noqa: F401
