"""Group-sharded (ZeRO) stages over the ``sharding`` mesh axis.

Reference surface (paths per SURVEY.md §2.4, lines unverified — file:§0):
  * python/paddle/distributed/sharding/group_sharded.py:§0
        group_sharded_parallel / save_group_sharded_model
  * …/fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py:§0
  * …/fleet/meta_parallel/sharding/group_sharded_stage2.py:§0
  * …/fleet/meta_parallel/sharding/group_sharded_stage3.py:§0

Semantics mapping (single-controller jax):
  stage 1  — optimizer accumulators are device_put with a NamedSharding that
             splits the first divisible dim over ``sharding``.
  stage 2  — gradients are additionally placed sharded before the update
             (the reduce-scatter: each device materialises only its grad
             shard); parameters stay replicated.
  stage 3  — parameters themselves are placed sharded and their
             ``_sharding_spec`` is set so compiled paths keep them sharded;
             eager ops all-gather on demand (XLA inserts the collective).
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Parameter, Tensor
from ...optimizer.optimizer import Optimizer
from ...parallel import mesh as _mesh
from ..collective import Group

logger = logging.getLogger(__name__)

__all__ = [
    "group_sharded_parallel", "save_group_sharded_model",
    "GroupShardedOptimizerStage2", "GroupShardedStage2", "GroupShardedStage3",
    "shard_spec_for",
]


def _sharding_group(group: Optional[Group]) -> Group:
    if group is not None:
        return group
    mesh = _mesh.ensure_mesh()
    # default to the dedicated sharding axis; fall back to dp (pure-ZeRO
    # runs where the whole world is the sharding group, reference default
    # group=None → world)
    axis = "sharding" if mesh.shape.get("sharding", 1) > 1 else "dp"
    return Group(axis, mesh)


def shard_spec_for(shape, axis: str, degree: int) -> P:
    """PartitionSpec that splits the first dim divisible by ``degree``;
    replicated if none is (reference pads/flattens instead — we keep the
    tensor shape and simply skip unshardable tensors)."""
    if degree <= 1:
        return P()
    for i, d in enumerate(shape):
        if d % degree == 0 and d > 0:
            return P(*([None] * i + [axis]))
    return P()


def _place(arr, mesh, spec: P, offload: bool = False):
    if offload:
        cpus = jax.devices("cpu")
        if cpus:
            return jax.device_put(arr, cpus[0])
    if mesh is None:
        return arr
    return jax.device_put(arr, NamedSharding(mesh, spec))


class GroupShardedOptimizerStage2:
    """Optimizer wrapper sharding accumulators (and, at stage 2, gradients)
    over the group axis. Parity surface of GroupShardedOptimizerStage2."""

    def __init__(self, params: List[Parameter], optim: Optimizer,
                 group: Optional[Group] = None, offload: bool = False,
                 shard_grads: bool = True, device: str = "tpu", **kwargs):
        self._optim = optim
        self._group = _sharding_group(group)
        self._offload = offload
        self._shard_grads = shard_grads
        self._params = list(params)
        self._mesh = self._group.mesh
        self._axis = self._group.axis
        self._degree = self._group.nranks
        # rank → param-name partition for checkpoint parity (greedy by size,
        # same objective as the reference's Stage-2 param2rank map)
        self.param2rank = _greedy_partition(self._params, self._degree)
        self._wrap_state_init()

    def _wrap_state_init(self):
        inner = self._optim
        orig_init = inner._init_state
        mesh, axis, deg, off = self._mesh, self._axis, self._degree, self._offload

        def sharded_init(p: Parameter):
            state = orig_init(p)
            pspec = getattr(p, "_sharding_spec", None)
            for k, v in state.items():
                if pspec is not None and tuple(v.shape) == tuple(p._value.shape):
                    # param-shaped slot of an mp/tp-sharded param: compose the
                    # sharding axis INTO the param's spec so eager placement
                    # agrees with the compiled step's derivation (a bare
                    # P(axis) here conflicted with jit in_shardings)
                    from ..fleet.hybrid_engine import _spec_with_axis0
                    nd = len(v.shape)
                    d0 = v.shape[0] if nd else 1
                    spec = _spec_with_axis0(pspec, axis, nd, d0, deg)
                else:
                    spec = shard_spec_for(v.shape, axis, deg)
                state[k] = _place(v, mesh, spec, offload=off)
            return state

        inner._init_state = sharded_init

    # -- delegation --------------------------------------------------------
    def __getattr__(self, item):
        return getattr(self._optim, item)

    @property
    def inner_opt(self):
        return self._optim

    def step(self):
        if self._shard_grads and self._degree > 1:
            # "reduce-scatter": grads materialise sharded over the group axis
            for p in self._params:
                g = p._grad_value
                if g is None:
                    continue
                spec = shard_spec_for(g.shape, self._axis, self._degree)
                p._grad_value = _place(g, self._mesh, spec)
        self._optim.step()
        # stage 2 keeps parameters replicated: re-place any param whose value
        # picked up the grad/state sharding during the update
        for p in self._params:
            if getattr(p, "_sharding_spec", None) is None:
                sh = getattr(p._value, "sharding", None)
                if sh is not None and getattr(sh, "spec", P()) != P():
                    p._value = _place(p._value, self._mesh, P())

    def clear_grad(self, *a, **k):
        self._optim.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._optim.state_dict()

    def set_state_dict(self, sd):
        return self._optim.set_state_dict(sd)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()


class _ShardedModelWrapper:
    """Common forward-delegating wrapper (reference stage wrappers subclass
    nn.Layer; here a thin proxy keeps the wrapped layer untouched)."""

    def __init__(self, layer):
        self._layers = layer

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.__dict__["_layers"], item)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def train(self):
        return self._layers.train()

    def eval(self):
        return self._layers.eval()


class GroupShardedStage2(_ShardedModelWrapper):
    """Gradient + optimizer-state sharding (ZeRO-2). The grad placement is
    driven by the wrapped GroupShardedOptimizerStage2 at step() time."""

    def __init__(self, layer, sharding_optimizer, group: Optional[Group] = None,
                 sync_buffers: bool = False, buffer_max_size: int = 2 ** 23,
                 auto_refresh_trainable: bool = True, device: str = "tpu",
                 dp_group: Optional[Group] = None, **kwargs):
        super().__init__(layer)
        self._sharding_optimizers = (
            sharding_optimizer if isinstance(sharding_optimizer, (list, tuple))
            else [sharding_optimizer])
        self._group = _sharding_group(group)

    def get_all_parameters(self):
        """Parity: stage-2 params are already full (replicated)."""
        return self._layers.parameters()


class GroupShardedStage3(_ShardedModelWrapper):
    """Parameter + gradient + optimizer-state sharding (ZeRO-3 / FSDP).

    Parameters are placed sharded over the group axis and tagged with
    ``_sharding_spec`` so compiled steps (jit.HybridTrainStep) keep them
    sharded; eager forward all-gathers on demand (XLA-inserted)."""

    def __init__(self, layer, optimizer=None, group: Optional[Group] = None,
                 sync_buffers: bool = False, device: str = "tpu",
                 segment_size: int = 2 ** 20, pertrain_sync_models: bool = True,
                 offload: bool = False, sync_comm: bool = False,
                 dp_group: Optional[Group] = None, exclude_layer=None, **kw):
        super().__init__(layer)
        self._group = _sharding_group(group)
        self._optimizer = optimizer
        mesh, axis, deg = self._group.mesh, self._group.axis, self._group.nranks
        for p in layer.parameters():
            if not p.trainable:
                continue
            spec = shard_spec_for(p._value.shape, axis, deg)
            if spec == P():
                continue
            prev = p._sharding_spec
            if prev is not None and tuple(prev) != ():
                continue  # TP-sharded params keep their TP spec
            p._value = _place(p._value, mesh, spec, offload=offload)
            p._sharding_spec = spec

    def get_all_parameters(self, convert2cpu: bool = False):
        """All-gather every sharded param back to full/replicated (reference:
        stage-3 allgather for save)."""
        mesh = self._group.mesh
        for p in self._layers.parameters():
            if getattr(p, "_sharding_spec", None) is not None and \
                    self._group.axis in _flat_axes(p._sharding_spec):
                p._value = _place(p._value, mesh, P())
                p._sharding_spec = None
        return self._layers.parameters()


def _flat_axes(spec) -> set:
    out = set()
    for d in tuple(spec):
        if d is None:
            continue
        for a in (d if isinstance(d, tuple) else (d,)):
            out.add(a)
    return out


def _greedy_partition(params: List[Parameter], degree: int):
    """Greedy size-balanced rank assignment (reference
    GroupShardedOptimizerStage2._partition_parameters /
    DygraphShardingOptimizer): largest-first onto the lightest rank."""
    sizes = [0] * max(degree, 1)
    mapping = {}
    for p in sorted(params, key=lambda q: -int(np.prod(q.shape or (1,)))):
        r = int(np.argmin(sizes))
        mapping[p.name] = r
        sizes[r] += int(np.prod(p.shape or (1,)))
    return mapping


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group: Optional[Group] = None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size: int = 2 ** 23,
                           segment_size: int = 2 ** 20, sync_comm: bool = False,
                           dp_group: Optional[Group] = None,
                           exclude_layer=None):
    """User API parity with paddle.distributed.sharding.group_sharded_parallel.

    level: 'os' (stage 1) | 'os_g' (stage 2) | 'p_g_os' (stage 3).
    Returns (model, optimizer, scaler).
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be one of 'os', 'os_g', 'p_g_os'")
    g = _sharding_group(group)
    params = list(model.parameters())
    if level in ("os", "os_g"):
        optimizer = GroupShardedOptimizerStage2(
            params, optimizer, group=g, offload=offload,
            shard_grads=(level == "os_g"))
        model = GroupShardedStage2(model, optimizer, group=g,
                                   sync_buffers=sync_buffers,
                                   buffer_max_size=buffer_max_size,
                                   dp_group=dp_group)
    else:
        model = GroupShardedStage3(model, optimizer=optimizer, group=g,
                                   sync_buffers=sync_buffers,
                                   segment_size=segment_size, offload=offload,
                                   sync_comm=sync_comm, dp_group=dp_group,
                                   exclude_layer=exclude_layer)
        optimizer = GroupShardedOptimizerStage2(
            params, optimizer, group=g, offload=offload, shard_grads=True)
    return model, optimizer, scaler


def save_group_sharded_model(model, output: str, optimizer=None):
    """Gather full params and save layer (and optimizer) state under
    ``output`` (reference writes model.pdmodel/opt.pdopt into a directory)."""
    from ...framework import io_save
    if os.path.splitext(output)[1]:
        raise ValueError("save_group_sharded_model expects a directory path")
    os.makedirs(output, exist_ok=True)
    target = model
    while isinstance(target, _ShardedModelWrapper):
        if isinstance(target, GroupShardedStage3):
            target.get_all_parameters()
        target = target.__dict__["_layers"]
    io_save.save(target.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        io_save.save(optimizer.state_dict(), os.path.join(output, "opt.pdopt"))
