"""``paddle_tpu.distributed.sharding`` — ZeRO group-sharded data parallelism.

Rebuild of python/paddle/distributed/sharding/group_sharded.py and
python/paddle/distributed/fleet/meta_parallel/sharding/ (SURVEY.md §2.4
Sharding row): stage 1 ("os") shards optimizer state, stage 2 ("os_g") also
shards gradients, stage 3 ("p_g_os") additionally shards parameters (FSDP).

TPU-native mechanism: instead of per-rank python bookkeeping + NCCL
reduce-scatter/allgather (reference GroupShardedStage2/3), shards are
expressed as ``NamedSharding`` placements over the ``sharding`` mesh axis.
XLA then materialises the reduce-scatter (grads), the sharded update
(optimizer state) and the on-demand all-gathers (stage-3 params) — in eager
mode via explicit ``device_put`` placement, in compiled steps via GSPMD
(jit.HybridTrainStep's ``zero_stage``).
"""

from .group_sharded import (  # noqa: F401
    GroupShardedOptimizerStage2,
    GroupShardedStage2,
    GroupShardedStage3,
    group_sharded_parallel,
    save_group_sharded_model,
    shard_spec_for,
)
