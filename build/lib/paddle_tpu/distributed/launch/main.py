"""Entry: ``python -m paddle_tpu.distributed.launch [opts] train.py ...``.

Reference: python/paddle/distributed/launch/main.py (SURVEY.md §2.6);
console-script ``fleetrun`` equivalent.
"""

from __future__ import annotations

import logging
import signal
import sys
from typing import List, Optional

from .context import Context
from .controller import CollectiveController


def launch(argv: Optional[List[str]] = None) -> int:
    ctx = Context(argv)
    logging.basicConfig(
        level=getattr(logging, ctx.args.log_level.upper(), logging.INFO),
        format="LAUNCH %(levelname)s %(asctime)s %(message)s")
    ctrl = CollectiveController(ctx)

    def _sig(_signum, _frame):
        ctrl.stop()
        sys.exit(130)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    return ctrl.run()


def main() -> None:
    sys.exit(launch())


if __name__ == "__main__":
    main()
