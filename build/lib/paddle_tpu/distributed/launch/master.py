"""Cross-node rendezvous master.

Reference: python/paddle/distributed/launch/controllers/master.py — HTTP or
etcd master doing peer registration + barrier (SURVEY.md §2.6, §3.1). Here
the native TCPStore (paddle_tpu/distributed/store.py, C++ daemon) plays the
role of both the HTTP master and etcd: rank assignment via atomic ``add``,
peer exchange via set/get, a generation counter for elastic re-sync (the
etcd membership-watch equivalent, SURVEY §3.6).
"""

from __future__ import annotations

import json
import socket
import time
from typing import List, Optional, Tuple

from ..store import TCPStore


def _local_addresses() -> set:
    addrs = {"127.0.0.1", "0.0.0.0", "localhost"}
    try:
        addrs.add(socket.gethostname())
        for info in socket.getaddrinfo(socket.gethostname(), None):
            addrs.add(info[4][0])
    except OSError:
        pass
    return addrs


class LocalMaster:
    """Single-node: everything is local, no store needed."""

    def __init__(self):
        self._gen = 0

    def sync_peers(self, endpoints: List[str], rank: int, nnodes_min: int,
                   nnodes_max: int, gen: int = 0) -> Tuple[int, List[List[str]]]:
        return 0, [endpoints]

    def get_gen(self) -> int:
        return self._gen

    def bump_gen(self) -> int:
        self._gen += 1
        return self._gen

    def close(self):
        pass


class StoreMaster:
    """Multi-node rendezvous over TCPStore.

    Each node publishes its worker endpoints under a generation namespace;
    node ranks are assigned first-come by an atomic counter unless pinned
    with --rank. With an elastic ``min:max`` node range, the first node
    closes membership once >= min nodes have settled (or max arrived).
    """

    def __init__(self, endpoint: str, node_ip: str, rank: int,
                 job_id: str = "default", timeout_s: float = 120.0,
                 settle_s: float = 3.0):
        host, port = endpoint.rsplit(":", 1)
        try:
            resolved = socket.gethostbyname(host)
        except OSError:
            resolved = host
        is_host = (rank == 0) or (
            rank < 0 and (resolved in _local_addresses() or
                          resolved == node_ip or host in _local_addresses()))
        self.store = TCPStore(host=resolved, port=int(port),
                              is_master=is_host, timeout=timeout_s)
        self.prefix = f"launch/{job_id}"
        self.timeout_s = timeout_s
        self.settle_s = settle_s

    def sync_peers(self, endpoints: List[str], rank: int, nnodes_min: int,
                   nnodes_max: int, gen: int = 0
                   ) -> Tuple[int, List[List[str]]]:
        """Register this node; return (node_rank, peers by node rank).

        Membership decision (elastic range): the rank-0 node waits until the
        join counter reaches ``nnodes_max``, or >= ``nnodes_min`` with no new
        arrivals for ``settle_s``, then publishes the agreed world under
        ``{ns}/world``; everyone else blocks on that key.
        """
        ns = f"{self.prefix}/g{gen}"
        # Pinned (--rank) and auto-assigned ranks cannot mix: an auto node
        # could collide with a pinned rank it cannot see. Fail fast — and do
        # it BEFORE joining the membership counter, so an aborting node does
        # not become a phantom member its peers wait on.
        mode = "pinned" if rank >= 0 else "auto"
        other = "auto" if mode == "pinned" else "pinned"
        self.store.add(f"{ns}/mode_{mode}", 1)
        if self.store.add(f"{ns}/mode_{other}", 0) > 0:
            raise RuntimeError(
                "rendezvous: some nodes pinned --rank while others did not; "
                "pin every node's rank or none")
        if rank < 0:
            rank = self.store.add(f"{ns}/node_counter", 1) - 1
        else:
            self.store.add(f"{ns}/node_counter", 1)
        self.store.set(f"{ns}/node/{rank}", json.dumps(endpoints))

        if rank == 0:
            deadline = time.monotonic() + self.timeout_s
            last_n, last_change = 0, time.monotonic()
            while True:
                n = self.store.add(f"{ns}/node_counter", 0)
                now = time.monotonic()
                if n != last_n:
                    last_n, last_change = n, now
                if n >= nnodes_max:
                    break
                if n >= nnodes_min and now - last_change >= self.settle_s:
                    break
                if now > deadline:
                    raise TimeoutError(
                        f"rendezvous: only {n}/{nnodes_min} nodes joined "
                        f"within {self.timeout_s}s")
                time.sleep(0.1)
            world = min(last_n, nnodes_max)
            self.store.set(f"{ns}/world", str(world))
        world = int(self.store.get(f"{ns}/world", timeout=self.timeout_s))
        if rank >= world:
            raise RuntimeError(
                f"node rank {rank} joined after membership closed at "
                f"{world} nodes (gen {gen}); wait for the next generation")
        peers: List[Optional[List[str]]] = [None] * world
        for i in range(world):
            raw = self.store.get(f"{ns}/node/{i}", timeout=self.timeout_s)
            peers[i] = json.loads(raw.decode())
        return rank, peers  # type: ignore[return-value]

    # -- elastic generation (etcd membership-watch equivalent) --------------

    def get_gen(self) -> int:
        return self.store.add(f"{self.prefix}/gen", 0)

    def bump_gen(self) -> int:
        return self.store.add(f"{self.prefix}/gen", 1)

    def close(self):
        self.store.close()


def make_master(master: Optional[str], node_ip: str, rank: int,
                job_id: str, is_multi_node: bool, timeout_s: float = 120.0):
    if not is_multi_node:
        return LocalMaster()
    if not master:
        raise ValueError(
            "--master ip:port is required for multi-node launch "
            "(it hosts the TCPStore rendezvous)")
    return StoreMaster(master, node_ip, rank, job_id=job_id,
                       timeout_s=timeout_s)
