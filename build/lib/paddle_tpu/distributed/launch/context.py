"""Launch context: CLI args + environment + device discovery.

Reference: python/paddle/distributed/launch/context/__init__.py,
args_envs.py, device.py, node.py (SURVEY.md §2.6). Env vars keep the
reference's ``PADDLE_*`` names so user scripts port unchanged.
"""

from __future__ import annotations

import argparse
import os
import socket
from dataclasses import dataclass, field
from typing import List, Optional


def free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def free_ports(n: int) -> List[int]:
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def node_ip() -> str:
    host = os.environ.get("POD_IP")
    if host:
        return host
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def detect_devices() -> int:
    """Number of local accelerator processes to spawn by default.

    TPU-first: one process per host (jax owns every local chip); honour
    ``PADDLE_NPROC_PER_NODE`` / CUDA-style visibility for tests.
    """
    env = os.environ.get("PADDLE_NPROC_PER_NODE")
    if env:
        return max(1, int(env))
    return 1


@dataclass
class Args:
    devices: Optional[str] = None
    nnodes: str = "1"
    nproc_per_node: Optional[int] = None
    master: Optional[str] = None
    rank: int = -1
    job_id: str = "default"
    log_dir: str = "log"
    log_level: str = "INFO"
    run_mode: str = "collective"
    max_restart: int = 3
    elastic_level: int = -1
    elastic_timeout: int = 30
    training_script: str = ""
    training_script_args: List[str] = field(default_factory=list)


def parse_args(argv: Optional[List[str]] = None) -> Args:
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training (TPU-native rebuild of "
                    "paddle.distributed.launch)")
    p.add_argument("--devices", "--gpus", "--xpus", dest="devices", default=None,
                   help="comma-separated local device ids (per-process mode)")
    p.add_argument("--nnodes", default=os.environ.get("PADDLE_NNODES", "1"),
                   help="node count, or elastic range 'min:max'")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="processes per node (default: one per host on TPU)")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="rendezvous endpoint ip:port (TCPStore)")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_RANK", "-1")),
                   help="node rank (optional; else assigned by master)")
    p.add_argument("--job_id", default=os.environ.get("PADDLE_JOB_ID", "default"))
    p.add_argument("--log_dir", default="log")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("--run_mode", default="collective",
                   choices=["collective"],
                   help="only collective mode (PS out of scope, SURVEY §2.7)")
    p.add_argument("--max_restart", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_MAX_RESTART", "3")))
    p.add_argument("--elastic_level", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_LEVEL", "-1")),
                   help="-1 off; >=1 restart local pod on worker fault")
    p.add_argument("--elastic_timeout", type=int, default=30)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    ns = p.parse_args(argv)
    return Args(**vars(ns))


class Context:
    """Parsed launch context (reference Context)."""

    def __init__(self, argv: Optional[List[str]] = None):
        self.args = parse_args(argv)
        self.envs = dict(os.environ)
        if ":" in self.args.nnodes:
            lo, hi = self.args.nnodes.split(":", 1)
            self.nnodes_min, self.nnodes_max = int(lo), int(hi)
            if self.args.elastic_level < 0:
                self.args.elastic_level = 1
        else:
            self.nnodes_min = self.nnodes_max = int(self.args.nnodes)
        if self.args.devices:
            self.local_nproc = len([d for d in self.args.devices.split(",") if d])
        elif self.args.nproc_per_node:
            self.local_nproc = self.args.nproc_per_node
        else:
            self.local_nproc = detect_devices()
        self.node_ip = node_ip() if self.nnodes_max > 1 else "127.0.0.1"

    @property
    def is_multi_node(self) -> bool:
        return self.nnodes_max > 1
