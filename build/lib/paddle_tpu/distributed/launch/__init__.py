"""Distributed launcher CLI.

Rebuild of ``python -m paddle.distributed.launch`` (reference:
python/paddle/distributed/launch/ — main.py, context/, controllers/, job/;
SURVEY.md §2.6, §3.1). TPU-first deltas:

- One worker process per **host** is the natural TPU unit (all local chips
  belong to one jax process); ``--nproc_per_node`` still allows per-device
  processes for CPU fake-cluster tests (the reference's per-GPU model).
- Rendezvous across nodes uses the native TCPStore
  (paddle_tpu.distributed.store) instead of the reference's HTTP/etcd master.
- Elastic recovery is restart-based (reference: fleet/elastic/manager.py):
  the watcher notices a dead container and relaunches the local pod up to
  ``--max_restart`` times.
"""

from .main import launch  # noqa: F401
