"""Job / Pod / Container process model.

Reference: python/paddle/distributed/launch/job/{job,pod,container,status}.py
(SURVEY.md §2.6, §3.1). A Container is one trainer subprocess with its
``PADDLE_*`` env and a per-rank log file (``workerlog.N`` — the primary
multi-process debugging surface, SURVEY §5.5).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


def build_trainer_env(rank: int, world: int, local_rank: int, local_size: int,
                      endpoint: str, all_endpoints: List[str], master: str,
                      node_rank: int = 0, job_id: str = "default",
                      restart_count: int = 0,
                      device: Optional[str] = None) -> Dict[str, str]:
    """The PADDLE_* env contract every trainer process receives — single
    source shared by the launch CLI and ``spawn`` so the two cannot drift."""
    return {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_LOCAL_SIZE": str(local_size),
        "PADDLE_NODE_RANK": str(node_rank),
        "PADDLE_CURRENT_ENDPOINT": endpoint,
        "PADDLE_TRAINER_ENDPOINTS": ",".join(all_endpoints),
        "PADDLE_MASTER": master,
        "PADDLE_JOB_ID": job_id,
        "PADDLE_RESTART_COUNT": str(restart_count),
        "FLAGS_selected_devices": device if device is not None else str(local_rank),
    }


class Status:
    UNINIT = "uninit"
    READY = "ready"
    RUNNING = "running"
    FAILED = "failed"
    TERMINATING = "terminating"
    COMPLETED = "completed"


class Container:
    """One trainer subprocess + env + log redirection."""

    def __init__(self, entrypoint: List[str], env: Dict[str, str],
                 log_path: Optional[str] = None, rank: int = -1):
        self.entrypoint = entrypoint
        self.env = env
        self.log_path = log_path
        self.rank = rank
        self.proc: Optional[subprocess.Popen] = None
        self._log_fh = None

    def start(self) -> None:
        env = dict(os.environ)
        env.update(self.env)
        stdout = stderr = None
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
            self._log_fh = open(self.log_path, "ab", buffering=0)
            stdout = stderr = self._log_fh
        self.proc = subprocess.Popen(self.entrypoint, env=env,
                                     stdout=stdout, stderr=stderr)

    @property
    def exit_code(self) -> Optional[int]:
        return self.proc.poll() if self.proc else None

    def status(self) -> str:
        if self.proc is None:
            return Status.UNINIT
        code = self.proc.poll()
        if code is None:
            return Status.RUNNING
        return Status.COMPLETED if code == 0 else Status.FAILED

    def terminate(self, force: bool = False) -> None:
        if self.proc is None or self.proc.poll() is not None:
            self._close_log()
            return
        self.proc.send_signal(signal.SIGKILL if force else signal.SIGTERM)
        try:
            self.proc.wait(timeout=8)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        self._close_log()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def _close_log(self):
        if self._log_fh is not None:
            try:
                self._log_fh.close()
            finally:
                self._log_fh = None

    def logs(self, tail: int = 4096) -> str:
        if not self.log_path or not os.path.exists(self.log_path):
            return ""
        with open(self.log_path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - tail))
            return f.read().decode(errors="replace")


class Pod:
    """The set of local containers on this node (reference Pod)."""

    def __init__(self, name: str = ""):
        self.name = name or f"pod-{os.getpid()}"
        self.containers: List[Container] = []
        self.restart_count = 0

    def add_container(self, entrypoint, env, log_path=None, rank=-1):
        self.containers.append(Container(entrypoint, env, log_path, rank))

    def deploy(self) -> None:
        for c in self.containers:
            c.start()

    def status(self) -> str:
        stats = [c.status() for c in self.containers]
        if any(s == Status.FAILED for s in stats):
            return Status.FAILED
        if any(s == Status.RUNNING for s in stats):
            return Status.RUNNING
        if stats and all(s == Status.COMPLETED for s in stats):
            return Status.COMPLETED
        return Status.UNINIT

    def join(self, poll_interval: float = 0.2) -> str:
        """Block until every container exits or one fails."""
        while True:
            s = self.status()
            if s in (Status.FAILED, Status.COMPLETED):
                return s
            time.sleep(poll_interval)

    def stop(self, force: bool = False) -> None:
        for c in self.containers:
            c.terminate(force=force)

    def reset(self) -> None:
        """Drop dead containers so the pod can be rebuilt for a restart."""
        self.stop(force=True)
        self.containers = []
        self.restart_count += 1


class Job:
    def __init__(self, job_id: str = "default"):
        self.id = job_id
        self.pod = Pod()
