"""Fleet — hybrid-parallel training facade.

Rebuild of python/paddle/distributed/fleet/ (fleet.init / distributed_model /
distributed_optimizer, DistributedStrategy.hybrid_configs — SURVEY.md §2.4,
§2.5). The strategy keys match the reference; the execution substrate is one
jax Mesh + the compiled hybrid engine.
"""

from .distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet_base import (  # noqa: F401
    init, distributed_model, distributed_optimizer, get_hybrid_communicate_group,
    worker_index, worker_num, is_first_worker, barrier_worker,
)
from ..topology import HybridCommunicateGroup, CommunicateTopology  # noqa: F401
from . import recompute as _recompute_mod  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from .hybrid_optimizer import HybridParallelOptimizer, HybridParallelClipGrad  # noqa: F401
from . import utils  # noqa: F401
from . import meta_optimizers  # noqa: F401
