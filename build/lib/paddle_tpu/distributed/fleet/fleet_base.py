"""Fleet facade.

Rebuild of python/paddle/distributed/fleet/fleet.py (fleet.init /
distributed_model / distributed_optimizer — SURVEY.md §2.4 hybrid row, §3.2
call stack).
"""

from __future__ import annotations

from typing import Optional

from .. import env as _env
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        set_hybrid_communicate_group,
                        get_hybrid_communicate_group)
from .distributed_strategy import DistributedStrategy
from ...parallel import mesh as _mesh

_state = {"strategy": None, "initialized": False}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level=None):
    """Parity with fleet.init: parse strategy, build topology + mesh, create
    axis groups."""
    strategy = strategy or DistributedStrategy()
    _state["strategy"] = strategy
    _env.init_parallel_env()
    degrees = strategy.degrees()
    order = strategy.hybrid_configs.get("order", list(_mesh.HYBRID_ORDER))
    # build the global mesh (folds leftover devices into dp) honouring the
    # configured axis order
    mesh = _mesh.build_mesh(degrees, order=order)
    _mesh.set_global_mesh(mesh)
    actual = {ax: mesh.shape[ax] for ax in mesh.axis_names}
    dims = [actual.get(ax, 1) for ax in _mesh.HYBRID_ORDER]
    topo = CommunicateTopology(list(_mesh.HYBRID_ORDER), dims)
    hcg = HybridCommunicateGroup(topo)
    set_hybrid_communicate_group(hcg)
    _state["initialized"] = True
    return None


def fleet_initialized() -> bool:
    return _state["initialized"]


def get_strategy() -> Optional[DistributedStrategy]:
    return _state["strategy"]


def _apply_recompute(model, checkpoints) -> None:
    """Wrap the named sublayers' forward in fleet.recompute (jax.checkpoint).

    ``checkpoints`` holds dotted sublayer paths (e.g. "llama.layers.0"); the
    reference's recompute pass marks segment boundaries by variable name —
    here the layer itself is the segment.
    """
    from .recompute import recompute as _rc

    for path in checkpoints:
        sub = model
        for part in str(path).split("."):
            sub = sub[int(part)] if part.isdigit() else getattr(sub, part)
        if getattr(sub, "_fleet_recompute_wrapped", False):
            continue
        orig = sub.forward

        def wrapped(*args, _orig=orig, **kwargs):
            return _rc(_orig, *args, **kwargs)

        sub.forward = wrapped
        sub._fleet_recompute_wrapped = True


def distributed_model(model):
    """Wrap per active parallelism (reference dispatch in fleet.py →
    PipelineParallel / TensorParallel / ShardingParallel wrappers), applying
    the strategy's model-side transforms (amp O2 cast, recompute)."""
    from ..meta_parallel.pipeline_parallel import PipelineParallel
    from ..meta_parallel.pp_layers import PipelineLayer
    from ..meta_parallel.parallel_wrapper import HybridParallelModel

    hcg = get_hybrid_communicate_group()
    strategy = _state["strategy"] or DistributedStrategy()
    if strategy.amp and strategy.amp_configs.get("level") == "O2":
        from ... import amp as _amp
        _amp.decorate(models=model, level="O2",
                      dtype=strategy.amp_configs.get("dtype", "bfloat16"))
    if strategy.recompute:
        ckpts = strategy.recompute_configs.get("checkpoints", [])
        if ckpts:
            _apply_recompute(model, ckpts)
    if hcg is not None and hcg.get_pipe_parallel_world_size() > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError(
                "pp_degree > 1 requires the model to be a PipelineLayer "
                "(parity with the reference)")
        return PipelineParallel(model, hcg, strategy)
    return HybridParallelModel(model, hcg, strategy)


def distributed_optimizer(optimizer, strategy=None):
    """Compose the strategy-selected meta-optimizers around the hybrid
    wrapper (reference: fleet.py _select_meta_optimizer over the registered
    meta-optimizer list)."""
    from .hybrid_optimizer import HybridParallelOptimizer
    from . import meta_optimizers as MO

    hcg = get_hybrid_communicate_group()
    strategy = strategy or _state["strategy"] or DistributedStrategy()
    opt = optimizer
    if getattr(strategy, "lamb", False):
        opt = MO.LambOptimizer(opt, getattr(strategy, "lamb_configs", None))
    # sharding (stage 1 wrap) + hybrid-aware grad clip
    opt = HybridParallelOptimizer(opt, hcg, strategy)
    if strategy.amp:
        opt = MO.AMPOptimizer(opt, strategy.amp_configs)
    if strategy.recompute:
        opt = MO.RecomputeOptimizer(opt, strategy.recompute_configs)
    if getattr(strategy, "gradient_merge", False):
        c = getattr(strategy, "gradient_merge_configs", {})
        opt = MO.GradientMergeOptimizer(opt, k_steps=c.get("k_steps", 1),
                                        avg=c.get("avg", True))
    if getattr(strategy, "localsgd", False):
        c = getattr(strategy, "localsgd_configs", {})
        opt = MO.LocalSGDOptimizer(opt, k_steps=c.get("k_steps", 1),
                                   begin_step=c.get("begin_step", 1))
    return opt


def get_hybrid_communicate_group_():
    return get_hybrid_communicate_group()


# re-export with the fleet.* names
def worker_index() -> int:
    return _env.get_rank()


def worker_num() -> int:
    return _env.get_world_size()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    import jax
    jax.effects_barrier()
