"""Gradient/param fusion buffers for bucketed communication.

Rebuild of python/paddle/distributed/fleet/utils/tensor_fusion_helper.py
(SURVEY.md §2.4 hybrid row): many small per-param collectives are fused into
a few flat-buffer collectives. On TPU this matters for the *DCN* (inter-
slice / data-parallel grad sync) path — ICI collectives live inside the
compiled step where XLA already fuses; eager DCN bucketing is where flat
buffers pay off, exactly like the reference's NCCL bucketing.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ....core.tensor import Tensor

ALIGN = 128  # flat-buffer slice alignment (lane-width friendly)


def _aligned(n: int) -> int:
    return (n + ALIGN - 1) // ALIGN * ALIGN


class FusedCommBuffer:
    """One flat fp32/bf16 buffer holding a bucket of param grads.

    ``add_grad`` packs a param's grad into its slice; once every param in
    the bucket has contributed, ``comm`` runs the provided collective on the
    single flat array and ``scatter_grads`` writes the slices back.
    """

    def __init__(self, id: int, params: Sequence, comm_group=None,
                 acc_steps: int = 1, use_main_grad: bool = False):
        self._id = id
        self._params = list(params)
        self._group = comm_group
        self._use_main_grad = use_main_grad
        self._offsets = {}
        off = 0
        for p in self._params:
            n = int(np.prod(p.shape)) if len(p.shape) else 1
            self._offsets[id_of(p)] = (off, n)
            off += _aligned(n)
        self._numel = off
        self._dtype = jnp.float32 if use_main_grad else None
        self.buffer = None
        self._pending = set(id_of(p) for p in self._params)

    def add_grad(self, param) -> None:
        g = param.main_grad if self._use_main_grad else param.grad
        assert g is not None, "param has no grad to fuse"
        v = g._value if isinstance(g, Tensor) else g
        if self.buffer is None:
            dt = self._dtype or v.dtype
            self.buffer = jnp.zeros((self._numel,), dt)
        off, n = self._offsets[id_of(param)]
        self.buffer = self.buffer.at[off:off + n].set(
            v.reshape(-1).astype(self.buffer.dtype))
        self._pending.discard(id_of(param))

    @property
    def all_grads_added(self) -> bool:
        return not self._pending

    def comm(self, collective_fn: Optional[Callable] = None) -> None:
        """Run the bucketed collective on the flat buffer.

        The buffer packs many params along dim 0, so the slab-view
        ``all_reduce`` (which shards dim 0 per rank) must NOT be used — it
        would sum different params' slices together. The default reduces
        with replicated semantics: every device holds the whole buffer and
        contributes it to a psum (result = nranks * buffer under one
        controller, matching the reference where identical per-rank grads
        sum to nranks·g; callers divide by the dp degree via ``scale``).
        """
        assert self.all_grads_added, "bucket incomplete"
        if collective_fn is not None:
            self.buffer = collective_fn(self.buffer)
            return
        from ... import collective as C
        self.buffer = C.all_reduce_replicated(self.buffer, group=self._group)

    def scatter_grads(self) -> None:
        """Write reduced slices back into each param's grad/main_grad."""
        for p in self._params:
            off, n = self._offsets[id_of(p)]
            sl = self.buffer[off:off + n].reshape(tuple(p.shape))
            if self._use_main_grad:
                p.main_grad = Tensor(sl.astype(jnp.float32))
            else:
                p.grad = Tensor(sl.astype(p._value.dtype))
        self._pending = set(id_of(p) for p in self._params)


def id_of(p) -> int:
    return id(p)


def fused_parameters(parameters: Sequence, group_size: int = 128 * 1024 * 1024,
                     comm_group=None, use_main_grad: bool = False,
                     dtype_bytes: int = 4) -> List[FusedCommBuffer]:
    """Partition params into buckets of ~group_size bytes (reference
    default 128MB) preserving order, one FusedCommBuffer per bucket."""
    buffers: List[FusedCommBuffer] = []
    bucket: List = []
    acc = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if len(p.shape) else 1
        sz = _aligned(n) * dtype_bytes
        if bucket and acc + sz > group_size:
            buffers.append(FusedCommBuffer(len(buffers), bucket, comm_group,
                                           use_main_grad=use_main_grad))
            bucket, acc = [], 0
        bucket.append(p)
        acc += sz
    if bucket:
        buffers.append(FusedCommBuffer(len(buffers), bucket, comm_group,
                                       use_main_grad=use_main_grad))
    return buffers
