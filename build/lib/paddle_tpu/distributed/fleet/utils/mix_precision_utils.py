"""main_grad mixed-precision utilities.

Rebuild of python/paddle/distributed/fleet/utils/mix_precision_utils.py
(MixPrecisionLayer / MixPrecisionOptimizer / MixPrecisionScaler — SURVEY.md
§2.5 AMP row). The reference accumulates each param's low-precision grads
into an fp32 ``main_grad`` buffer (via a backward post-hook, fused on GPU by
fused_linear_param_grad_add) so multi-microbatch accumulation and clipping
run in fp32.

TPU-first note: bf16 training needs no loss scaling, but fp32 *accumulation*
still matters for long grad-accumulation chains; inside the compiled hybrid
step the same effect comes from keeping the grad-accum buffer fp32 (XLA
donation, ops/fused_linear.py). This module is the eager/dygraph surface.
"""

from __future__ import annotations

import jax.numpy as jnp

from ....core.tensor import Tensor


class MixPrecisionLayer:
    """Wraps a Layer: after each ``backward()``, fold every param's grad into
    its fp32 ``main_grad`` and clear the low-precision grad."""

    def __init__(self, layers, dtype: str = "bfloat16"):
        self._layers = layers
        self._dtype = dtype

    def __getattr__(self, item):
        return getattr(self._layers, item)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def accumulate_main_grads(self) -> None:
        """Fold ``.grad`` → ``.main_grad`` (fp32) for every parameter.

        Call after each microbatch's backward (the reference does this in a
        param backward post-hook; the eager tape here has no per-param hook
        point, so it is one explicit sweep)."""
        for p in self._layers.parameters():
            g = p.grad
            if g is None:
                continue
            g32 = g._value.astype(jnp.float32)
            if p.main_grad is None:
                p.main_grad = Tensor(g32)
            else:
                p.main_grad = Tensor(p.main_grad._value + g32)
            p.clear_grad()


class MixPrecisionOptimizer:
    """Wraps an optimizer to step from ``main_grad`` instead of ``.grad``."""

    def __init__(self, optimizer):
        self._inner_opt = optimizer

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        params = self._inner_opt._parameter_list
        saved = []
        for p in params:
            if p.main_grad is not None:
                saved.append((p, p._grad_value))
                p._grad_value = p.main_grad._value
        try:
            self._inner_opt.step()
        finally:
            for p, old in saved:
                p._grad_value = old

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._inner_opt._parameter_list:
            if set_to_zero and p.main_grad is not None:
                p.main_grad = Tensor(jnp.zeros_like(p.main_grad._value))
            else:
                p.main_grad = None
        self._inner_opt.clear_grad(set_to_zero=False)


def unwrap_optimizer(optimizer):
    opt = optimizer
    while isinstance(opt, MixPrecisionOptimizer):
        opt = opt._inner_opt
    return opt
