"""Stage-1 sharding optimizer (Fleet dygraph path).

Rebuild of python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py:§0 (SURVEY.md §2.4 Sharding row): ZeRO stage 1 —
each sharding rank owns the optimizer state (and update) of a size-balanced
subset of parameters, then broadcasts updated params over the sharding group.

TPU-native mechanism: the rank→param partition is kept for parity (and for
the distributed checkpointer), but the actual state sharding is expressed as
NamedSharding placement over the ``sharding`` mesh axis — the broadcast is
XLA's job. ``split_param`` (stage-1 v2: shard *within* each tensor) is the
placement default here, since dim-splitting is the natural mesh expression.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...optimizer.optimizer import Optimizer
from ..collective import Group
from ..sharding.group_sharded import (GroupShardedOptimizerStage2,
                                      _greedy_partition)


class DygraphShardingOptimizer(GroupShardedOptimizerStage2):
    """Parity class name; behaviour = stage-1 (opt-state only — grads stay
    replicated, matching the reference's stage 1)."""

    def __init__(self, optimizer: Optimizer, hcg=None):
        group = None
        if hcg is not None:
            group = hcg.get_sharding_parallel_group()
        params = list(optimizer._parameter_list)
        super().__init__(params, optimizer, group=group, shard_grads=False)

    # reference helpers used by callers/tests
    def _partition_parameters(self):
        rank2params = {}
        for name, r in self.param2rank.items():
            rank2params.setdefault(r, []).append(name)
        return {r: sorted(v) for r, v in rank2params.items()}

    @property
    def _rank2params(self):
        return self._partition_parameters()
