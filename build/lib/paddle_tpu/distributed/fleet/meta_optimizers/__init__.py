"""Strategy-driven meta-optimizers.

Reference: python/paddle/distributed/fleet/meta_optimizers/ — static-graph
rewrite passes (AMP, recompute, sharding, pipeline, gradient-merge, localsgd,
lamb, ...) selected by DistributedStrategy flags (SURVEY.md §2.5). jax has no
separate static graph to rewrite, so each optimizer here performs the
TPU-native form of its transform directly: wrapping the optimizer (AMP master
weights + loss scaler, gradient merge accumulation, localsgd periodic
averaging, Lamb swap) or carrying the config the model-side transform reads
(recompute). ``fleet.distributed_optimizer`` composes them in the reference's
order; ``unwrap_optimizer`` reaches the base optimizer through any stack of
wrappers (the compiled HybridTrainStep needs the raw update rule).
"""

from __future__ import annotations

from typing import Optional


def unwrap_optimizer(opt):
    """Follow the wrapper chain (_inner_opt / inner_opt) to the base
    Optimizer carrying the actual update rule and accumulators."""
    seen = set()
    while id(opt) not in seen:
        seen.add(id(opt))
        nxt = getattr(opt, "_inner_opt", None) or getattr(opt, "inner_opt", None)
        if nxt is None:
            return opt
        opt = nxt
    return opt


class _DelegatingMetaOptimizer:
    """Wraps an inner optimizer; subclasses attach their transform."""

    def __init__(self, optimizer):
        self.inner_opt = optimizer

    def __getattr__(self, item):
        if item == "inner_opt":  # not yet set (unpickling) → no recursion
            raise AttributeError(item)
        return getattr(self.inner_opt, item)

    def step(self):
        self.inner_opt.step()

    def clear_grad(self, *a, **k):
        self.inner_opt.clear_grad(*a, **k)

    def clear_gradients(self, *a, **k):
        # dynamic dispatch so subclass clear_grad overrides (gradient merge)
        # are honoured through the legacy alias too
        return self.clear_grad(*a, **k)

    def state_dict(self):
        return self.inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self.inner_opt.set_state_dict(sd)


class AMPOptimizer(_DelegatingMetaOptimizer):
    """amp strategy → master weights (O2) + a configured GradScaler.

    bf16 (TPU default) needs no loss scaling, so the scaler enables dynamic
    scaling only for float16 — same decision the reference encodes in its
    amp pass defaults (fp16 lineage).
    """

    def __init__(self, optimizer, configs: Optional[dict] = None):
        super().__init__(optimizer)
        c = dict(configs or {})
        self.amp_level = c.get("level", "O1")
        self.amp_dtype = c.get("dtype", "bfloat16")
        base = unwrap_optimizer(optimizer)
        if self.amp_level == "O2":
            base._multi_precision = True
        from ....amp import GradScaler

        self.scaler = GradScaler(
            enable=(self.amp_dtype == "float16"
                    and c.get("use_dynamic_loss_scaling", True)),
            init_loss_scaling=c.get("init_loss_scaling", 2.0 ** 15),
            incr_ratio=c.get("incr_ratio", 2.0),
            decr_ratio=c.get("decr_ratio", 0.5),
            incr_every_n_steps=c.get("incr_every_n_steps", 1000),
            decr_every_n_nan_or_inf=c.get("decr_every_n_nan_or_inf", 2),
        )

    def get_loss_scaler(self):
        return self.scaler


class RecomputeOptimizer(_DelegatingMetaOptimizer):
    """recompute strategy: the transform is model-side (jax.checkpoint via
    fleet.recompute, applied by fleet.distributed_model on the layers named
    in recompute_configs['checkpoints']); this wrapper carries the config."""

    def __init__(self, optimizer, configs: Optional[dict] = None):
        super().__init__(optimizer)
        self.recompute_configs = dict(configs or {})


class ShardingOptimizer(_DelegatingMetaOptimizer):
    """sharding strategy → DygraphShardingOptimizer / group_sharded APIs
    (selected inside HybridParallelOptimizer when sharding_degree > 1)."""


class PipelineOptimizer(_DelegatingMetaOptimizer):
    """pipeline strategy → meta_parallel.PipelineParallel engines."""


class GradientMergeOptimizer(_DelegatingMetaOptimizer):
    """k-step gradient accumulation: ``step`` applies the update only every
    k-th call (grads keep accumulating on the tape between them), optionally
    averaging; ``clear_grad`` drops grads only after a real update."""

    def __init__(self, optimizer, k_steps: int = 1, avg: bool = True):
        super().__init__(optimizer)
        self._k = max(int(k_steps), 1)
        self._avg = bool(avg)
        self._calls = 0
        self._stepped = False

    def step(self):
        self._calls += 1
        if self._calls % self._k:
            self._stepped = False
            return
        if self._avg and self._k > 1:
            base = unwrap_optimizer(self.inner_opt)
            for p in base._parameter_list:
                if getattr(p, "_grad_value", None) is not None:
                    p._grad_value = p._grad_value / self._k
                mg = getattr(p, "main_grad", None)
                if mg is not None:
                    mg._value = mg._value / self._k
        self.inner_opt.step()
        self._stepped = True

    def clear_grad(self, *a, **k):
        if self._stepped:  # between accumulation steps grads must survive
            self.inner_opt.clear_grad(*a, **k)


class LambOptimizer(_DelegatingMetaOptimizer):
    """lamb strategy → swap the update rule for paddle_tpu.optimizer.Lamb,
    keeping the caller's lr/parameters/clip."""

    def __init__(self, optimizer, configs: Optional[dict] = None):
        from ....optimizer import Lamb

        base = unwrap_optimizer(optimizer)
        c = dict(configs or {})
        exclude = list(c.get("exclude_from_weight_decay", []))
        exclude_fn = None
        if exclude:
            def exclude_fn(p):
                name = getattr(p, "name", "") or ""
                return any(frag in name for frag in exclude)
        lamb = Lamb(
            learning_rate=base._learning_rate,
            lamb_weight_decay=c.get("lamb_weight_decay", 0.01),
            parameters=base._parameter_list,
            grad_clip=base._grad_clip,
            exclude_from_weight_decay_fn=exclude_fn,
        )
        super().__init__(lamb)


class LocalSGDOptimizer(_DelegatingMetaOptimizer):
    """localsgd: inner step every call; every k_steps the parameters are
    averaged over the dp group (reference: paddle.distributed collectives on
    params outside the hot loop)."""

    def __init__(self, optimizer, k_steps: int = 1, group=None,
                 begin_step: int = 1):
        super().__init__(optimizer)
        self._k = max(int(k_steps), 1)
        self._group = group
        self._begin = max(int(begin_step), 0)
        self._calls = 0

    def step(self):
        self.inner_opt.step()
        self._calls += 1
        if self._calls >= self._begin and self._calls % self._k == 0:
            self._average_parameters()

    def _average_parameters(self):
        from ... import collective as C

        g = C.get_group(self._group)
        if g.nranks <= 1:
            return
        base = unwrap_optimizer(self.inner_opt)
        for p in base._parameter_list:
            p._value = C.all_reduce_replicated(p._value, op="avg", group=g)


class DGCOptimizer(_DelegatingMetaOptimizer):
    """deep gradient compression: not applicable on ICI (collectives are
    compiler-scheduled); kept for strategy-surface parity."""
