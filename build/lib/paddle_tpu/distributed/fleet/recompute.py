"""Activation recomputation (checkpointing).

Rebuild of python/paddle/distributed/fleet/recompute/{recompute,
recompute_hybrid}.py (SURVEY.md §2.5). The reference replays CUDA RNG state
and re-runs forward in backward; on TPU this is ``jax.checkpoint`` — RNG
replay is free because dropout keys are pure values.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...jit.functional import bind, tree_unwrap


def _find_layers(function):
    """Collect Layers whose parameters must be lifted into the checkpointed
    region: the function itself, its bound self, partial args, and any Layer
    captured in its closure (the `lambda x: self.block(x)` pattern)."""
    import functools as _ft
    from ...nn.layer import Layer

    found = []

    def add(obj):
        if isinstance(obj, Layer) and all(obj is not l for l in found):
            found.append(obj)

    add(function)
    add(getattr(function, "__self__", None))
    if isinstance(function, _ft.partial):
        for a in list(function.args) + list(function.keywords.values()):
            add(a)
        add(getattr(function.func, "__self__", None))
    closure = getattr(function, "__closure__", None)
    if closure:
        for cell in closure:
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            add(v)
            add(getattr(v, "__self__", None))
    return [l for l in found if l is not None]


def recompute(function: Callable, *args, use_reentrant=True,
              preserve_rng_state=True, **kwargs):
    """Run ``function(*args)`` under rematerialisation: activations inside are
    not saved; they are recomputed in backward.

    Parameters of any Layer reachable from ``function`` (itself, bound self,
    partial args, closure cells) are lifted into the checkpointed region so
    their gradients flow on the tape.
    """
    layers = _find_layers(function)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]

    plists = []  # per-layer (names, tensors)
    ptensors = []
    for layer in layers:
        plist = [(n, p) for n, p in layer.named_parameters()]
        plists.append([n for n, _ in plist])
        ptensors.extend(p for _, p in plist)

    np_ = len(ptensors)

    # Side-channel attributes (MoE gate aux losses) written onto sublayers
    # DURING the call would escape the checkpoint region as tracers; instead
    # they are threaded out as extra checkpoint outputs and written back
    # outside. aux_subs is populated at trace time (dict dedupes the
    # fwd + remat-bwd traces).
    aux_subs: dict = {}
    meta: dict = {}

    def pure(*vals):
        pvals_flat = vals[:np_]
        tvals = vals[np_:]
        full = list(args)
        for i, v in zip(tensor_pos, tvals):
            full[i] = Tensor(v, stop_gradient=False)

        def run():
            out = function(*full, **kwargs)
            auxvals = []
            for layer in layers:
                for name, sub in layer.named_sublayers(include_self=True):
                    la = getattr(sub, "l_aux", None)
                    if isinstance(la, Tensor):
                        aux_subs[(id(layer), name)] = sub
                        auxvals.append(la._value)
            leaves, treedef = jax.tree_util.tree_flatten(tree_unwrap(out))
            meta["treedef"] = treedef
            meta["n_out"] = len(leaves)
            return tuple(leaves) + tuple(auxvals)

        import contextlib
        with contextlib.ExitStack() as stack:
            off = 0
            for layer, names in zip(layers, plists):
                pvals = dict(zip(names, pvals_flat[off:off + len(names)]))
                off += len(names)
                stack.enter_context(bind(layer, pvals))
            return run()

    ck = jax.checkpoint(pure)
    outs = apply(lambda *v: ck(*v), *ptensors, *tensor_args,
                 op_name="recompute")
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    n_out = meta["n_out"]
    out = jax.tree_util.tree_unflatten(meta["treedef"], outs[:n_out])
    for sub, av in zip(aux_subs.values(), outs[n_out:]):
        sub.l_aux = av if isinstance(av, Tensor) else Tensor(av)
    return out


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Parity with recompute_sequential: checkpoint each segment of a
    Sequential."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    n = len(funcs)
    seg_size = max(n // max(segments, 1), 1)
    x = args[0] if len(args) == 1 else args
    i = 0
    while i < n:
        seg = funcs[i:i + seg_size]
        for f in seg:
            x = recompute(f, x)
        i += seg_size
    return x


def recompute_hybrid(ctx, function, *args, **kwargs):
    """mp-aware recompute (reference offloads + per-mp-rank seeds). RNG keys
    make seed replay automatic; offload maps to XLA remat/offload policies."""
    return recompute(function, *args, **kwargs)
