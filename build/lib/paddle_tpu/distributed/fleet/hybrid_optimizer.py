"""Hybrid-parallel optimizer wrapper.

Rebuild of python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py (HybridParallelOptimizer + HybridParallelClipGrad
— SURVEY.md §2.4 hybrid row).

In the reference, clip must psum squared norms across mp/pp/sharding NCCL
groups because each process sees only its shard. In the single-controller
rebuild, *eager* state is global (norms are already global), and in the
*compiled* hybrid step GSPMD computes global norms automatically from sharded
values — so HybridParallelClipGrad degenerates to ClipGradByGlobalNorm with
distributed-parameter awareness kept for the manual (shard_map) path, where it
psums over the active axes exactly like the reference.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ...optimizer.clip import ClipGradByGlobalNorm
from ...optimizer.optimizer import Optimizer
from ...parallel import pcontext


class HybridParallelClipGrad(ClipGradByGlobalNorm):
    def __init__(self, clip, hcg):
        clip_norm = getattr(clip, "clip_norm", clip)
        super().__init__(float(clip_norm))
        self._hcg = hcg

    def global_norm(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads if g is not None]
        if not sq:
            return jnp.asarray(0.0, jnp.float32)
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        # manual mode: shards are per-device → psum over every active axis the
        # parameters are split across (mp + sharding + pp)
        if pcontext.in_manual_mode():
            for kind in ("mp", "sharding", "pp"):
                ax = pcontext.manual_axis(kind)
                if ax is not None:
                    total = lax.psum(total, ax)
        return jnp.sqrt(total)


class HybridParallelOptimizer:
    """Delegating wrapper: swaps the inner clip for the hybrid-aware clip and
    keeps the reference's API (step/clear_grad/state_dict/…)."""

    def __init__(self, optimizer, hcg, strategy):
        from .meta_optimizers import unwrap_optimizer

        # reference: when sharding_degree > 1 the inner optimizer is wrapped
        # in DygraphShardingOptimizer (stage 1) before the hybrid wrapper
        if hcg is not None and hcg.get_sharding_parallel_world_size() > 1 and \
                isinstance(unwrap_optimizer(optimizer), Optimizer) and \
                not self._already_sharded(optimizer):
            from .dygraph_sharding_optimizer import DygraphShardingOptimizer
            optimizer = DygraphShardingOptimizer(optimizer, hcg)
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        # reference behaviour: only ClipGradByGlobalNorm is swapped for the
        # hybrid-aware variant; other clip types keep their own semantics.
        inner = unwrap_optimizer(optimizer)
        if isinstance(inner._grad_clip, ClipGradByGlobalNorm) and \
                not isinstance(inner._grad_clip, HybridParallelClipGrad) and \
                hcg is not None:
            inner._grad_clip = HybridParallelClipGrad(
                inner._grad_clip, hcg)

    @staticmethod
    def _already_sharded(optimizer) -> bool:
        from .dygraph_sharding_optimizer import DygraphShardingOptimizer
        o = optimizer
        seen = set()
        while o is not None and id(o) not in seen:
            seen.add(id(o))
            if isinstance(o, DygraphShardingOptimizer):
                return True
            o = getattr(o, "_inner_opt", None) or getattr(o, "inner_opt", None)
        return False

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    @property
    def inner_opt(self):
        return self._inner_opt

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        return self._inner_opt.minimize(loss, *a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
