"""Compiled hybrid-parallel train step (GSPMD path).

This is the heart of the Fleet rebuild: ONE jitted XLA program implementing
forward + backward + clip + optimizer update, partitioned over the global
mesh via NamedShardings:

* **dp**: batch dim sharded over ('dp','sharding') — gradient psums are
  inserted by XLA (replaces EagerReducer bucketed allreduce, SURVEY.md §2.3).
* **mp (TP)**: weights carry specs from meta_parallel.mp_layers; XLA inserts
  the c_identity/mp_allreduce collectives the reference codes by hand.
* **sharding (ZeRO)**: stage 1/2 shard optimizer state (and grads via XLA's
  reduce-scatter dataflow); stage 3 additionally shards parameters (FSDP) —
  reference: DygraphShardingOptimizer / GroupShardedStage2/3 (SURVEY.md §2.4).
* **sp (sequence parallel)**: activation specs via sequence_parallel_utils.

Pipeline parallelism uses the shard_map engine instead (pipeline_parallel.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...jit import TrainStep
from ...jit.functional import param_arrays, buffer_arrays, tree_unwrap
from ...core.tensor import Tensor
from ...parallel import mesh as _mesh


def _spec_with_axis0(spec: P, axis: str, ndim: int, dim0: int, degree: int) -> P:
    """Add `axis` to dim 0 of spec if free and divisible."""
    dims = list(spec) + [None] * (ndim - len(list(spec)))
    used = set()
    for d in dims:
        if d is None:
            continue
        for a in (d if isinstance(d, tuple) else (d,)):
            used.add(a)
    if axis in used or ndim == 0 or degree <= 1 or dim0 % degree != 0:
        return P(*dims) if dims else P()
    if dims[0] is None:
        dims[0] = axis
    elif isinstance(dims[0], tuple):
        dims[0] = tuple(list(dims[0]) + [axis])
    else:
        dims[0] = (dims[0], axis)
    return P(*dims)


class HybridTrainStep(TrainStep):
    """TrainStep + mesh shardings. Used directly or via
    fleet.distributed_model(...).compile_train_step(...)."""

    def __init__(self, model, loss_fn: Callable, optimizer, mesh=None,
                 zero_stage: int = 1, batch_axes=("dp", "sharding"),
                 donate: bool = True):
        super().__init__(model, loss_fn, optimizer, donate=donate)
        self.mesh = mesh if mesh is not None else _mesh.ensure_mesh()
        self.zero_stage = int(zero_stage)
        self.batch_axes = tuple(ax for ax in batch_axes
                                if ax in self.mesh.shape and self.mesh.shape[ax] > 1)
        self._shardings_built = False

    # -- sharding derivation -------------------------------------------------
    def _param_spec(self, p) -> P:
        spec = p._sharding_spec if p._sharding_spec is not None else P()
        if self.zero_stage >= 3 and p.trainable:
            deg = self.mesh.shape.get("sharding", 1)
            nd = len(p._value.shape)
            d0 = p._value.shape[0] if nd else 1
            spec = _spec_with_axis0(spec, "sharding", nd, d0, deg)
        return spec

    def _build_shardings(self, batch):
        mesh = self.mesh
        ns = lambda spec: NamedSharding(mesh, spec)
        params_sh = {}
        by_name = dict(self.model.named_parameters())
        for name, p in by_name.items():
            params_sh[name] = ns(self._param_spec(p))
        opt_sh = {}
        deg = mesh.shape.get("sharding", 1)
        for name, p in self._trainable:
            pspec = self._param_spec(p)
            slots = {}
            state = self.optimizer._accumulators[id(p)]
            for slot, v in state.items():
                cur = getattr(v, "sharding", None)
                if isinstance(cur, NamedSharding) and cur.mesh == mesh:
                    # state already placed (eager stage-1/2 wrapper): the jit
                    # in_shardings must match the actual placement exactly
                    slots[slot] = cur
                    continue
                vshape = getattr(v, "shape", ())
                if tuple(vshape) == tuple(p._value.shape) and self.zero_stage >= 1:
                    nd = len(vshape)
                    d0 = vshape[0] if nd else 1
                    slots[slot] = ns(_spec_with_axis0(pspec, "sharding", nd, d0, deg))
                else:
                    slots[slot] = ns(P())
            opt_sh[name] = slots
        buf_sh = {name: ns(P()) for name in buffer_arrays(self.model)}
        batch_spec = P(self.batch_axes if self.batch_axes else None)
        batch_sh = jax.tree_util.tree_map(
            lambda v: ns(batch_spec if getattr(v, "ndim", 0) > 0 else P()), batch)
        rep = ns(P())
        self._in_sh = (params_sh, opt_sh, buf_sh, batch_sh, rep, rep, rep)
        self._out_sh = (rep, params_sh, opt_sh, buf_sh)
        self._shardings_built = True

    # override: derive shardings from the first batch, then jit with them
    def __call__(self, *batch):
        if self._jitted is None:
            self._build_shardings(tree_unwrap(batch))
            donate = (0, 1, 2) if self._donate else ()
            self._jitted = jax.jit(self._make_step_fn(), donate_argnums=donate,
                                   in_shardings=self._in_sh,
                                   out_shardings=self._out_sh)
        return super().__call__(*batch)
