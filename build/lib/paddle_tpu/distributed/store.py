"""TCPStore — KV rendezvous store bootstrapping distributed jobs.

Parity with the reference's TCPStore/MasterDaemon
(paddle/fluid/distributed/store/tcp_store.{h,cc}:§0, pybind
paddle/fluid/pybind/communication.cc:§0 — SURVEY.md §2.3). The daemon and
client are native C++ (paddle_tpu/core/native/tcp_store.cc) loaded via
ctypes; a pure-Python implementation of the same wire protocol is the
fallback, and the two interoperate (a Python client can talk to a C++
daemon and vice versa).

On TPU the heavy lifting of device coordination belongs to
jax.distributed's coordination service; TCPStore covers *framework-level*
rendezvous: launch-CLI peer registration, elastic membership, barriers.
"""

from __future__ import annotations

import ctypes
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

_CMD_SET, _CMD_GET, _CMD_ADD, _CMD_WAIT, _CMD_DEL = 1, 2, 3, 4, 5


def _load_native():
    if os.environ.get("PADDLE_TPU_DISABLE_NATIVE", "0") == "1":
        return None
    from ..core import native
    path = native.ensure_built("tcp_store")
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.ts_master_start.restype = ctypes.c_void_p
    lib.ts_master_start.argtypes = [ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_int)]
    lib.ts_master_stop.argtypes = [ctypes.c_void_p]
    lib.ts_client_connect.restype = ctypes.c_void_p
    lib.ts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                      ctypes.c_int]
    lib.ts_client_close.argtypes = [ctypes.c_void_p]
    lib.ts_set.restype = ctypes.c_int
    lib.ts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                           ctypes.c_int]
    lib.ts_get.restype = ctypes.c_int
    lib.ts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                           ctypes.c_char_p, ctypes.c_int,
                           ctypes.POINTER(ctypes.c_int)]
    lib.ts_add.restype = ctypes.c_int
    lib.ts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                           ctypes.POINTER(ctypes.c_int64)]
    lib.ts_wait.restype = ctypes.c_int
    lib.ts_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.ts_del.restype = ctypes.c_int
    lib.ts_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


_native_lib = None
_native_tried = False
_native_lock = threading.Lock()


def native_lib():
    global _native_lib, _native_tried
    with _native_lock:
        if not _native_tried:
            _native_lib = _load_native()
            _native_tried = True
        return _native_lib


# --------------------------------------------------------- Python daemon
class _PyMasterDaemon:
    """Pure-Python master speaking the tcp_store.cc wire protocol."""

    def __init__(self, port: int = 0):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("0.0.0.0", port))
        self._srv.listen(128)
        self.port = self._srv.getsockname()[1]
        self._kv: Dict[bytes, bytes] = {}
        self._cond = threading.Condition()
        self._stop = False
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop:
                hdr = _recv_exact(conn, 5)
                if hdr is None:
                    return
                cmd, klen = struct.unpack("<BI", hdr)
                key = _recv_exact(conn, klen)
                if key is None:
                    return
                if cmd == _CMD_SET:
                    raw = _recv_exact(conn, 4)
                    if raw is None:
                        return
                    (vlen,) = struct.unpack("<I", raw)
                    val = _recv_exact(conn, vlen) if vlen else b""
                    if val is None:
                        return
                    with self._cond:
                        self._kv[key] = val
                        self._cond.notify_all()
                    conn.sendall(struct.pack("<BI", 0, 0))
                elif cmd in (_CMD_GET, _CMD_WAIT):
                    raw = _recv_exact(conn, 8)
                    if raw is None:
                        return
                    (timeout_ms,) = struct.unpack("<q", raw)
                    deadline = (None if timeout_ms < 0
                                else time.monotonic() + timeout_ms / 1000.0)
                    # Build the reply under the lock, send OUTSIDE it — a
                    # slow client draining a large value must not stall
                    # every other connection's SET/ADD/GET.
                    with self._cond:
                        while key not in self._kv:
                            rem = (None if deadline is None
                                   else deadline - time.monotonic())
                            if rem is not None and rem <= 0:
                                break
                            self._cond.wait(timeout=0.2 if rem is None
                                            else min(rem, 0.2))
                            if self._stop:
                                return
                        if key in self._kv:
                            val = self._kv[key] if cmd == _CMD_GET else b""
                            msg = struct.pack("<BI", 0, len(val)) + val
                        else:
                            msg = struct.pack("<BI", 1, 0)
                    conn.sendall(msg)
                elif cmd == _CMD_ADD:
                    raw = _recv_exact(conn, 8)
                    if raw is None:
                        return
                    (delta,) = struct.unpack("<q", raw)
                    with self._cond:
                        cur = int(self._kv.get(key, b"0") or b"0") + delta
                        self._kv[key] = str(cur).encode()
                        self._cond.notify_all()
                    val = str(cur).encode()
                    conn.sendall(struct.pack("<BI", 0, len(val)) + val)
                elif cmd == _CMD_DEL:
                    with self._cond:
                        self._kv.pop(key, None)
                    conn.sendall(struct.pack("<BI", 0, 0))
                else:
                    return
        except OSError:
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        with self._cond:
            self._cond.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


class MasterDaemon:
    """Owns the store state; runs on exactly one process (the master)."""

    def __init__(self, port: int = 0, prefer_native: bool = True):
        self._native = None
        self._py = None
        lib = native_lib() if prefer_native else None
        if lib is not None:
            out_port = ctypes.c_int(0)
            h = lib.ts_master_start(port, ctypes.byref(out_port))
            if h:
                self._native = (lib, ctypes.c_void_p(h))
                self.port = out_port.value
                self.backend = "native"
                return
        self._py = _PyMasterDaemon(port)
        self.port = self._py.port
        self.backend = "python"

    def stop(self):
        if self._native is not None:
            lib, h = self._native
            lib.ts_master_stop(h)
            self._native = None
        if self._py is not None:
            self._py.stop()
            self._py = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


# --------------------------------------------------------------- client
class _PyClient:
    def __init__(self, host: str, port: int, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        last_err = None
        while True:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=2.0)
                break
            except OSError as e:  # master may not be up yet
                last_err = e
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"TCPStore: cannot reach {host}:{port}: {last_err}")
                time.sleep(0.1)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._lock = threading.Lock()

    def request(self, cmd: int, key: bytes, payload: bytes) -> Tuple[int, bytes]:
        with self._lock:
            self._sock.sendall(struct.pack("<BI", cmd, len(key)) + key
                               + payload)
            hdr = _recv_exact(self._sock, 5)
            if hdr is None:
                raise ConnectionError("TCPStore: connection lost")
            st, vlen = struct.unpack("<BI", hdr)
            val = _recv_exact(self._sock, vlen) if vlen else b""
            if val is None:
                raise ConnectionError("TCPStore: connection lost")
            return st, val

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class TCPStore:
    """Client handle; when ``is_master`` also hosts the daemon in-process.

    API parity with the reference's pybind surface: ``set``/``get``/``add``/
    ``wait``/``delete_key``/``barrier``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 300.0, prefer_native: bool = True):
        self.daemon = None
        if is_master:
            self.daemon = MasterDaemon(port, prefer_native=prefer_native)
            port = self.daemon.port
            host = "127.0.0.1"
        self.host, self.port = host, port
        self.world_size = world_size
        self.timeout = timeout
        self._native = None
        self._py = None
        lib = native_lib() if prefer_native else None
        if lib is not None:
            h = lib.ts_client_connect(host.encode(), port,
                                      int(timeout * 1000))
            if h:
                self._native = (lib, ctypes.c_void_p(h))
        if self._native is None:
            self._py = _PyClient(host, port, timeout)
        # one in-flight request per connection (the native client shares a
        # single fd; interleaved requests would corrupt the wire stream)
        self._req_lock = threading.Lock()

    @property
    def backend(self) -> str:
        return "native" if self._native is not None else "python"

    def set(self, key: str, value) -> None:
        val = value.encode() if isinstance(value, str) else bytes(value)
        if self._native is not None:
            lib, h = self._native
            with self._req_lock:
                rc = lib.ts_set(h, key.encode(), val, len(val))
            if rc != 0:
                raise ConnectionError(f"TCPStore.set({key}) rc={rc}")
        else:
            st, _ = self._py.request(_CMD_SET, key.encode(),
                                     struct.pack("<I", len(val)) + val)
            if st != 0:
                raise ConnectionError(f"TCPStore.set({key}) status={st}")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        tmo = self.timeout if timeout is None else timeout
        tmo_ms = -1 if tmo is None else int(tmo * 1000)
        if self._native is not None:
            lib, h = self._native
            cap = 1 << 16
            while True:
                buf = ctypes.create_string_buffer(cap)
                out_len = ctypes.c_int(0)
                with self._req_lock:
                    rc = lib.ts_get(h, key.encode(), tmo_ms, buf, cap,
                                    ctypes.byref(out_len))
                if rc == -2:
                    cap *= 16
                    continue
                if rc == 1:
                    raise TimeoutError(f"TCPStore.get({key}) timed out")
                if rc != 0:
                    raise ConnectionError(f"TCPStore.get({key}) rc={rc}")
                return buf.raw[:out_len.value]
        st, val = self._py.request(_CMD_GET, key.encode(),
                                   struct.pack("<q", tmo_ms))
        if st == 1:
            raise TimeoutError(f"TCPStore.get({key}) timed out")
        return val

    def add(self, key: str, delta: int = 1) -> int:
        if self._native is not None:
            lib, h = self._native
            out = ctypes.c_int64(0)
            with self._req_lock:
                rc = lib.ts_add(h, key.encode(), delta, ctypes.byref(out))
            if rc != 0:
                raise ConnectionError(f"TCPStore.add({key}) rc={rc}")
            return out.value
        st, val = self._py.request(_CMD_ADD, key.encode(),
                                   struct.pack("<q", delta))
        if st != 0:
            raise ConnectionError(f"TCPStore.add({key}) status={st}")
        return int(val)

    def wait(self, key: str, timeout: Optional[float] = None) -> None:
        tmo = self.timeout if timeout is None else timeout
        tmo_ms = -1 if tmo is None else int(tmo * 1000)
        if self._native is not None:
            lib, h = self._native
            with self._req_lock:
                rc = lib.ts_wait(h, key.encode(), tmo_ms)
            if rc == 1:
                raise TimeoutError(f"TCPStore.wait({key}) timed out")
            if rc != 0:
                raise ConnectionError(f"TCPStore.wait({key}) rc={rc}")
            return
        st, _ = self._py.request(_CMD_WAIT, key.encode(),
                                 struct.pack("<q", tmo_ms))
        if st == 1:
            raise TimeoutError(f"TCPStore.wait({key}) timed out")

    def delete_key(self, key: str) -> None:
        if self._native is not None:
            lib, h = self._native
            with self._req_lock:
                lib.ts_del(h, key.encode())
        else:
            self._py.request(_CMD_DEL, key.encode(), b"")

    def barrier(self, name: str = "default",
                timeout: Optional[float] = None) -> None:
        """All ``world_size`` clients must call; built on add+set+wait.

        Round numbering is server-side (a per-name sequence counter), so a
        client created later (elastic rejoin) enters the barrier round its
        peers are currently in rather than replaying round 1.
        """
        seq = self.add(f"/barrier/{name}/seq", 1)
        rnd = (seq - 1) // self.world_size
        key = f"/barrier/{name}/r{rnd}"
        n = self.add(key, 1)
        if n == self.world_size:
            self.set(key + "/done", b"1")
            if rnd > 0:
                # everyone has left round rnd-1 (they added for this round),
                # so its keys are dead — reclaim them or the master's map
                # grows two keys per barrier for the life of the job
                prev = f"/barrier/{name}/r{rnd - 1}"
                self.delete_key(prev)
                self.delete_key(prev + "/done")
        self.wait(key + "/done", timeout)

    def close(self):
        if self._native is not None:
            lib, h = self._native
            lib.ts_client_close(h)
            self._native = None
        if self._py is not None:
            self._py.close()
            self._py = None
        if self.daemon is not None:
            self.daemon.stop()
            self.daemon = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
