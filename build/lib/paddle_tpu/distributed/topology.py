"""Hybrid-parallel topology.

Rebuild of CommunicateTopology / HybridCommunicateGroup
(python/paddle/distributed/fleet/base/topology.py — SURVEY.md §2.4 hybrid
row). The reference builds a cartesian rank grid and one NCCL group per axis;
here the grid IS a jax Mesh and each "group" is a mesh-axis handle
(collective.Group). Rank→coordinate bijection matches the reference's order
["dp", "pp", "sharding", "sep", "mp"].
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .collective import Group
from ..parallel import mesh as _mesh


class CommunicateTopology:
    def __init__(self, hybrid_group_names: List[str] = None,
                 dims: List[int] = None):
        self._parallel_names = hybrid_group_names or list(_mesh.HYBRID_ORDER)
        self._dims = dims or [1] * len(self._parallel_names)
        self._world_size = int(np.prod(self._dims))
        shape = tuple(self._dims)
        self._coord_of_rank = {}
        for rank in range(self._world_size):
            self._coord_of_rank[rank] = np.unravel_index(rank, shape)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return int(np.ravel_multi_index(coord, tuple(self._dims)))

    def get_coord(self, rank: int):
        return tuple(int(c) for c in self._coord_of_rank[rank])

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(r for r, c in self._coord_of_rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along the axis: lists of world ranks that differ only in
        this coordinate."""
        axis = self._parallel_names.index(axis_name)
        groups: Dict[tuple, List[int]] = {}
        for rank, coord in self._coord_of_rank.items():
            key = tuple(c for i, c in enumerate(coord) if i != axis)
            groups.setdefault(key, []).append(rank)
        return [sorted(v) for _, v in sorted(groups.items())]

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self.get_rank(**dict(zip(self._parallel_names, coord)))


class HybridCommunicateGroup:
    """Axis-group query API (parity with the reference class of the same
    name). Groups returned are mesh-axis handles usable with
    distributed.collective functions and inside compiled programs."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self._dp_degree = topology.get_dim("dp")
        self._pp_degree = topology.get_dim("pp")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = topology.get_dim("mp")
        self.global_rank = 0
        degrees = {n: topology.get_dim(n) for n in topology.get_hybrid_group_names()}
        mesh = _mesh.get_global_mesh()
        if mesh is None or dict(zip(mesh.axis_names, [mesh.shape[a] for a in mesh.axis_names])) != \
                {ax: degrees.get(ax, 1) for ax in _mesh.HYBRID_ORDER}:
            try:
                mesh = _mesh.build_mesh(degrees)
                _mesh.set_global_mesh(mesh)
            except ValueError:
                mesh = _mesh.get_global_mesh()
        self.mesh = mesh

    # degrees -------------------------------------------------------------
    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sep_parallel_world_size(self) -> int:
        return self._sep_degree

    # ranks (single-controller: coordinate of "this" process is 0; in-program
    # coordinates come from lax.axis_index) --------------------------------
    def get_data_parallel_rank(self) -> int:
        return 0

    def get_model_parallel_rank(self) -> int:
        return 0

    def get_stage_id(self) -> int:
        return 0

    def get_sharding_parallel_rank(self) -> int:
        return 0

    def get_sep_parallel_rank(self) -> int:
        return 0

    # groups ---------------------------------------------------------------
    def get_data_parallel_group(self) -> Group:
        return Group("dp", self.mesh)

    def get_model_parallel_group(self) -> Group:
        return Group("mp", self.mesh)

    def get_pipe_parallel_group(self) -> Group:
        return Group("pp", self.mesh)

    def get_sharding_parallel_group(self) -> Group:
        return Group("sharding", self.mesh)

    def get_sep_parallel_group(self) -> Group:
        return Group("sep", self.mesh)

    def get_check_parallel_group(self, *a, **k) -> Group:
        return Group("mp", self.mesh)

    def get_data_parallel_group_src_rank(self) -> int:
        return 0

    def get_model_parallel_group_src_rank(self) -> int:
        return 0

    # pipe helpers ---------------------------------------------------------
    def is_first_stage(self) -> bool:
        # single controller executes every stage, so it is both first and last
        return True

    def is_last_stage(self) -> bool:
        return True

    def get_p2p_groups(self):
        return None

    def topology(self) -> CommunicateTopology:
        return self._topo


_hcg: List[Optional[HybridCommunicateGroup]] = [None]


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    _hcg[0] = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg[0]
