from .api import ProcessMesh, Shard, Replicate, Partial, shard_tensor, reshard, dtensor_from_fn  # noqa: F401
from .engine import Engine, shard_layer  # noqa: F401
