"""Auto-parallel Engine: fit/evaluate/predict over a sharded model.

Rebuild of python/paddle/distributed/auto_parallel/static/engine.py
(SURVEY.md §2.4 auto-parallel row). The reference Engine drives the static
completion → partition → reshard pipeline; on TPU that pipeline IS
jit + GSPMD, so the Engine here: (1) honours parameter/tensor placements
installed by ``shard_tensor``/``shard_layer``, (2) compiles one donated
train step (jit.TrainStep) and reuses it across the epoch loop, (3) keeps
the reference's fit/evaluate/predict surface.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer import Layer


class Engine:
    def __init__(self, model: Layer, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy
        self._train_step = None
        self.history: list = []

    # -- internals -----------------------------------------------------------

    def _loader(self, data, batch_size):
        from ...io import DataLoader, Dataset
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=False)

    def _build_train_step(self):
        from ...jit import TrainStep
        loss_fn = self.loss

        def step_loss(model, *batch):
            *xs, y = batch
            out = model(*xs)
            return loss_fn(out, y)

        self._train_step = TrainStep(self.model, step_loss, self.optimizer)

    # -- public surface (reference Engine) -----------------------------------

    def fit(self, train_data, epochs: int = 1, batch_size: int = 1,
            steps_per_epoch: Optional[int] = None, log_freq: int = 10,
            verbose: int = 0):
        assert self.loss is not None and self.optimizer is not None, \
            "Engine.fit needs loss and optimizer"
        if self._train_step is None:
            self._build_train_step()
        loader = self._loader(train_data, batch_size)
        for epoch in range(epochs):
            losses = []
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = self._train_step(*batch)
                losses.append(float(loss))
                if verbose and step % log_freq == 0:
                    print(f"epoch {epoch} step {step}: loss {losses[-1]:.5f}")
            self.history.append({"epoch": epoch,
                                 "loss": float(np.mean(losses))})
        return self.history

    def evaluate(self, valid_data, batch_size: int = 1,
                 steps: Optional[int] = None):
        assert self.loss is not None
        from ...core import autograd as _ag
        loader = self._loader(valid_data, batch_size)
        losses = []
        with _ag.no_grad():
            self.model.eval()
            for step, batch in enumerate(loader):
                if steps is not None and step >= steps:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                *xs, y = batch
                out = self.model(*[x if isinstance(x, Tensor) else Tensor(x)
                                   for x in xs])
                losses.append(float(self.loss(out, y if isinstance(y, Tensor)
                                              else Tensor(y))))
            self.model.train()
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, test_data, batch_size: int = 1,
                steps: Optional[int] = None):
        from ...core import autograd as _ag
        loader = self._loader(test_data, batch_size)
        outs = []
        with _ag.no_grad():
            self.model.eval()
            for step, batch in enumerate(loader):
                if steps is not None and step >= steps:
                    break
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                xs = batch[:-1] if len(batch) > 1 else batch
                out = self.model(*[x if isinstance(x, Tensor) else Tensor(x)
                                   for x in xs])
                outs.append(np.asarray(out._value))
            self.model.train()
        return outs


def shard_layer(layer: Layer, process_mesh, shard_fn: Optional[Callable] = None,
                input_fn=None, output_fn=None) -> Layer:
    """Parity with paddle.distributed.shard_layer: place every parameter
    according to ``shard_fn(name, layer, process_mesh) -> placements`` (or
    replicate when no fn is given)."""
    from .api import Replicate, shard_tensor

    for name, param in layer.named_parameters():
        placements = None
        if shard_fn is not None:
            placements = shard_fn(name, layer, process_mesh)
        if placements is None:
            placements = [Replicate() for _ in process_mesh.shape]
        sharded = shard_tensor(param, process_mesh, placements,
                               stop_gradient=param.stop_gradient)
        param._value = sharded._value
        param._sharding_spec = sharded._sharding_spec
    return layer
