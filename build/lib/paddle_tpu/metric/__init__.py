"""Metrics, parity with python/paddle/metric/metrics.py (SURVEY.md §5.5)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        p = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        l = np.asarray(label._value if isinstance(label, Tensor) else label)
        if l.ndim == p.ndim:
            l = l.squeeze(-1)
        topk_idx = np.argsort(-p, axis=-1)[..., : self.maxk]
        correct = topk_idx == l[..., None]
        return correct

    def update(self, correct, *args):
        correct = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        n = correct[..., 0].size
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(axis=-1).sum()
            self.count[i] += n
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds).round()
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds).round()
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).ravel()
        if p.ndim == 2:
            p = p[:, -1]
        idx = np.minimum((p * self.num_thresholds).astype(int), self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    m = Accuracy(topk=(k,))
    correct = m.compute(input, label)
    acc = m.update(correct)
    return Tensor(np.asarray(acc, dtype=np.float32))
