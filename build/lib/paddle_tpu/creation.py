"""Tensor creation ops (``paddle.to_tensor``, ``zeros``, ``rand`` …).

Parity with python/paddle/tensor/creation.py + random.py of the reference
(SURVEY.md §2.1 op corpus). Random ops draw from the framework PRNG state
(paddle_tpu.random), so ``paddle_tpu.seed`` makes runs reproducible and the
jit machinery can thread traced keys through.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .core.dispatch import apply, unwrap
from .core.dtype import convert_dtype, get_default_dtype
from .core.tensor import Tensor, Parameter
from . import random as _random


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(unwrap(shape)))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    if isinstance(data, Tensor):
        v = data._value
    else:
        v = data
    d = convert_dtype(dtype)
    if d is None and isinstance(v, (list, tuple, int, float)):
        probe = np.asarray(v)
        if probe.dtype == np.float64:
            d = get_default_dtype()
    arr = jnp.asarray(v, dtype=d)
    return Tensor(arr, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), dtype=convert_dtype(dtype) or get_default_dtype()))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), dtype=convert_dtype(dtype) or get_default_dtype()))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    fill_value = unwrap(fill_value) if isinstance(fill_value, Tensor) else fill_value
    return Tensor(jnp.full(_shape(shape), fill_value,
                           dtype=convert_dtype(dtype) or get_default_dtype()))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    return apply(lambda v: jnp.zeros_like(v, dtype=convert_dtype(dtype)), x,
                 op_name="zeros_like")


def ones_like(x, dtype=None, name=None) -> Tensor:
    return apply(lambda v: jnp.ones_like(v, dtype=convert_dtype(dtype)), x,
                 op_name="ones_like")


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    return apply(lambda v: jnp.full_like(v, fill_value, dtype=convert_dtype(dtype)), x,
                 op_name="full_like")


empty_like = zeros_like


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    start = unwrap(start) if isinstance(start, Tensor) else start
    end = unwrap(end) if isinstance(end, Tensor) else end
    step = unwrap(step) if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        d = jnp.int64 if builtins_all_int(start, end, step) else get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=d))


def builtins_all_int(*xs) -> bool:
    return all(isinstance(x, (int, np.integer)) for x in xs)


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.linspace(start, stop, int(num),
                               dtype=convert_dtype(dtype) or get_default_dtype()))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=convert_dtype(dtype) or get_default_dtype()))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows), num_columns and int(num_columns),
                          dtype=convert_dtype(dtype) or get_default_dtype()))


def meshgrid(*args, **kwargs):
    tensors = [x if isinstance(x, Tensor) else Tensor(x) for x in
               (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = apply(lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *tensors,
                 op_name="meshgrid")
    return list(outs)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def fn(v):
        n = v.shape[-1]
        out = jnp.zeros(v.shape + (n,), v.dtype)
        idx = jnp.arange(n)
        return out.at[..., idx, idx].set(v)
    return apply(fn, x, op_name="diag_embed")


# ---------------------------------------------------------------------------
# random creation
# ---------------------------------------------------------------------------
def rand(shape, dtype=None, name=None) -> Tensor:
    k = _random.next_key()
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(k, _shape(shape), dtype=d))


def randn(shape, dtype=None, name=None) -> Tensor:
    k = _random.next_key()
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.normal(k, _shape(shape), dtype=d))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    k = _random.next_key() if seed == 0 else jax.random.key(seed)
    d = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(k, _shape(shape), dtype=d, minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = unwrap(mean) if isinstance(mean, Tensor) else mean
        s = unwrap(std) if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(np.shape(m), np.shape(s))
        k = _random.next_key()
        return Tensor(jax.random.normal(k, shp) * s + m)
    k = _random.next_key()
    return Tensor(jax.random.normal(k, _shape(shape)) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    k = _random.next_key()
    return Tensor(jax.random.randint(k, _shape(shape), low, high,
                                     dtype=convert_dtype(dtype)))


def randperm(n, dtype="int64", name=None) -> Tensor:
    k = _random.next_key()
    return Tensor(jax.random.permutation(k, int(n)).astype(convert_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    k = _random.next_key()

    def fn(v):
        logits = jnp.log(jnp.maximum(v, 1e-30))
        if replacement or num_samples == 1:
            return jax.random.categorical(k, logits, axis=-1,
                                          shape=v.shape[:-1] + (num_samples,)).astype(jnp.int64)
        # without replacement: gumbel top-k trick
        g = jax.random.gumbel(k, v.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(jnp.int64)

    return apply(fn, x, op_name="multinomial")


def bernoulli(x, name=None) -> Tensor:
    k = _random.next_key()
    return apply(lambda v: jax.random.bernoulli(k, v).astype(v.dtype), x,
                 op_name="bernoulli")


def create_parameter(shape, dtype=None, default_initializer=None, is_bias=False,
                     attr=None, name=None) -> Parameter:
    from .nn import initializer as I
    d = convert_dtype(dtype) or get_default_dtype()
    init = default_initializer or (I.Constant(0.0) if is_bias else I.XavierNormal())
    value = init(_shape(shape), d)
    return Parameter(value, name=name)
