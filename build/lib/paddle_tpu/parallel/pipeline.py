"""Pipeline-parallel execution over a mesh axis (shard_map + ppermute).

TPU-native rebuild of the reference's PipelineParallel engine
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py +
pp_utils/p2p_communication.py — SURVEY.md §2.4 PP row). Instead of NCCL
send/recv between trainer processes, the whole pipeline is ONE compiled XLA
program: stages live on submeshes of the ``pp`` axis, activations rotate with
``lax.ppermute`` over ICI, and the microbatch loop is a ``lax.scan`` — XLA
overlaps the permute DMA with the next microbatch's compute, which is the
latency-hiding the reference gets from its separate comm stream.

Schedule: GPipe-style fill-drain (all-forward then AD-driven all-backward).
The bubble fraction is (S-1)/(M+S-1); interleaved/1F1B variants change peak
memory, not bubble math, and remat (jax.checkpoint on stage_fn) recovers the
memory the way 1F1B would.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(stage_fn: Callable, stage_params: Any, microbatches,
                  axis_name: str = "pp"):
    """Run inside shard_map. Executes the fill-drain pipeline.

    stage_fn(params, x) -> y : one stage's computation (same structure on
        every stage; per-stage weights come pre-sliced by shard_map).
    microbatches: (M, ...) — microbatch-major input, replicated over the pp
        axis (only stage 0 reads it).
    Returns (M, ...) outputs — valid on the LAST stage, zeros elsewhere.

    This is exactly the one-chunk-per-device special case of the
    interleaved schedule below; delegating keeps a single scan skeleton.
    """
    lifted = jax.tree_util.tree_map(lambda a: a[None], stage_params)
    return pipeline_spmd_interleaved(stage_fn, lifted, microbatches,
                                     num_chunks=1, axis_name=axis_name)


def last_stage_broadcast(x, axis_name: str = "pp"):
    """Broadcast the last pp-stage's value to all stages (psum of a mask)."""
    S = lax.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    return lax.psum(jnp.where(sid == S - 1, x, jnp.zeros_like(x)), axis_name)


def stage_slice_info(axis_name: str = "pp"):
    """(stage_id, num_stages) inside shard_map."""
    return lax.axis_index(axis_name), lax.axis_size(axis_name)


# ---------------------------------------------------------------------------
# Interleaved (virtual pipeline) schedule
# ---------------------------------------------------------------------------
def interleave_chunk_order(num_stages: int, num_chunks: int):
    """Host-side pre-permutation for the stacked chunk-param array.

    Model chunk j (contiguous layer block j of S*v) lives on device j % S
    (Megatron interleave assignment). shard_map shards the leading dim in
    contiguous blocks, so the stacked array must be reordered such that
    device d's block [d*v:(d+1)*v] holds model chunks (d, d+S, d+2S, ...):
    order[d*v + i] = d + i*S.
    """
    return [d + i * num_stages
            for d in range(num_stages) for i in range(num_chunks)]


def pipeline_spmd_interleaved(chunk_fn, chunk_params, microbatches,
                              num_chunks: int, axis_name: str = "pp"):
    """Interleaved virtual-pipeline schedule as ONE systolic scan.

    Reference: PipelineParallelWithInterleave (SURVEY.md §2.4 PP row).
    Each device holds ``v = num_chunks`` model chunks (chunk_params leaves:
    leading dim v, pre-arranged via :func:`interleave_chunk_order`). Every
    scan tick performs exactly one chunk-step per device and one ring
    ppermute; the work item of device d at tick t is

        w = t - d,  local chunk slot i = (w % (S*v)) // S,
        microbatch m = (w // (S*v)) * S + (w % S)

    which makes the ring deliver precisely the activation each device
    needs one tick before it needs it (the Megatron interleave order,
    with chunk boundaries crossing the ring seam d=S-1 → d=0 landing on
    slot i+1). Fill/drain bubble: S-1 *chunk*-ticks out of M*v + S - 1
    total — the v-fold bubble reduction over fill-drain, expressed so XLA
    overlaps the ppermute DMA with the next tick's compute.

    microbatches: (M, ...) with M % S == 0, replicated over the pp axis.
    Returns (M, ...) outputs — valid on the LAST stage, zeros elsewhere.
    """
    S = lax.axis_size(axis_name)
    d = lax.axis_index(axis_name)
    v = num_chunks
    M = microbatches.shape[0]
    if v > 1 and M % S != 0:
        # the (slot, m) decomposition below needs whole microbatch groups;
        # v == 1 reduces to m = w, valid for any M
        raise ValueError(f"microbatch count {M} must divide by stages {S}")
    bad = [a.shape[0] for a in jax.tree_util.tree_leaves(chunk_params)
           if a.shape[0] != v]
    if bad:
        # dynamic_index_in_dim clamps, which would silently reuse a chunk
        raise ValueError(
            f"chunk_params leaves must have leading dim {v}, got {bad}")
    total_work = M * v
    T = total_work + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    state = jnp.zeros(microbatches.shape[1:], microbatches.dtype)
    outs = jnp.zeros(microbatches.shape, microbatches.dtype)

    def step(carry, t):
        state, outs = carry
        w = t - d
        valid = jnp.logical_and(w >= 0, w < total_work)
        wc = jnp.clip(w, 0, total_work - 1)
        slot = (wc % (S * v)) // S
        m = (wc // (S * v)) * S + (wc % S)
        inject = microbatches[m]
        x = jnp.where(jnp.logical_and(d == 0, slot == 0), inject, state)
        p_slot = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, slot, 0, keepdims=False),
            chunk_params)
        y = chunk_fn(p_slot, x)
        emit = jnp.logical_and(valid,
                               jnp.logical_and(d == S - 1, slot == v - 1))
        outs = jnp.where(
            emit, lax.dynamic_update_index_in_dim(outs, y, m, 0), outs)
        state = lax.ppermute(y, axis_name, perm)
        return (state, outs), None

    (state, outs), _ = lax.scan(step, (state, outs), jnp.arange(T))
    return outs
