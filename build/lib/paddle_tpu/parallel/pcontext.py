"""Manual-parallel execution context.

When the hybrid engine runs model code inside ``jax.shard_map`` (pp>1 or
explicit-collective mode), layers must issue explicit ``lax.psum`` /
``all_gather`` over named mesh axes — the Megatron execution style of the
reference's mp_layers (python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py, SURVEY.md §2.4). Outside shard_map (GSPMD
path / eager), the same layers run with sharding annotations instead.

This context tells layer code which mode it is in and which axis names carry
which parallelism dimension.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

_state = {
    "manual": False,          # True inside shard_map
    "axes": {},               # parallelism name -> mesh axis name, e.g. {"mp": "mp"}
}


def in_manual_mode() -> bool:
    return _state["manual"]


def manual_axis(kind: str) -> Optional[str]:
    """Mesh axis name for 'mp' / 'dp' / 'pp' / 'sharding' / 'sep' / 'expert',
    or None if that dimension is not active (degree 1)."""
    return _state["axes"].get(kind)


@contextlib.contextmanager
def manual_parallel(axes: Dict[str, str]):
    prev = dict(_state)
    _state["manual"] = True
    _state["axes"] = dict(axes)
    try:
        yield
    finally:
        _state.update(prev)
