"""``paddle_tpu.save`` / ``load`` — single-process checkpoint tier.

Rebuild of python/paddle/framework/io.py (SURVEY.md §5.4 tier 1): state dicts
are pickled with tensors converted to numpy (bfloat16 stored via ml_dtypes
view). Distributed sharded checkpoints live in distributed.checkpoint.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, Parameter


class _TensorPayload:
    """Pickle-stable tensor container (dtype name + raw bytes + shape)."""

    def __init__(self, arr):
        a = np.asarray(arr)
        self.dtype = str(a.dtype)
        self.shape = a.shape
        if a.dtype == jnp.bfloat16:
            self.dtype = "bfloat16"
            self.data = a.view(np.uint16).tobytes()
        else:
            self.data = a.tobytes()

    def to_numpy(self):
        if self.dtype == "bfloat16":
            u16 = np.frombuffer(self.data, dtype=np.uint16).reshape(self.shape)
            return u16.view(jnp.bfloat16)
        return np.frombuffer(self.data, dtype=np.dtype(self.dtype)).reshape(self.shape)


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj._value)
    if isinstance(obj, (jnp.ndarray,)) or type(obj).__module__.startswith("jax"):
        try:
            return _TensorPayload(obj)
        except Exception:
            return obj
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    if isinstance(obj, _TensorPayload):
        return Tensor(jnp.asarray(obj.to_numpy()))
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **kwargs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, **kwargs):
    with open(path, "rb") as f:
        return _unpack(pickle.load(f))
