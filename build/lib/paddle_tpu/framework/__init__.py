"""Framework-plane utilities (save/load, dtype/device context).

Parity with python/paddle/framework/ of the reference (SURVEY.md §5.4 tier 1:
paddle.save/load — python/paddle/framework/io.py).
"""

from . import io_save  # noqa: F401
from .io_save import save, load  # noqa: F401
from ..core.dtype import set_default_dtype, get_default_dtype  # noqa: F401
