"""``paddle.linalg`` parity namespace.

Reference: python/paddle/tensor/linalg.py + python/paddle/linalg.py:§0.
Decompositions and solvers delegate to jnp.linalg (XLA lowers QR/SVD/
eigh/cholesky natively; on TPU these run in fp32 on the MXU where shapes
allow). Everything funnels through the dispatch `apply` so autograd and
profiler hooks see them.
"""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply


def _op(name, fn, *args, **static):
    return apply(fn, *args, op_name=name, **static)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    from .core import math_ops as M
    return M.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    # paddle semantics (flattened vector norm when axis is None) — shared
    # with the tensor-method implementation
    from .core import math_ops as M
    return M.norm(x, p=p, axis=axis, keepdim=keepdim)


def cond(x, p=None, name=None):
    return _op("cond", lambda v: jnp.linalg.cond(v, p=p), x)


def inv(x, name=None):
    return _op("inv", lambda v: jnp.linalg.inv(v), x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _op("pinv", lambda v: jnp.linalg.pinv(v, rtol=rcond,
                                                 hermitian=hermitian), x)


def det(x, name=None):
    return _op("det", lambda v: jnp.linalg.det(v), x)


def slogdet(x, name=None):
    def fn(v):
        sign, logabs = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logabs])
    return _op("slogdet", fn, x)


def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return _op("cholesky", fn, x)


def qr(x, mode="reduced", name=None):
    if mode == "r":
        # jnp returns the bare R matrix here — tuple() would split rows
        return _op("qr", lambda v: jnp.linalg.qr(v, mode="r"), x)
    return _op("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), x)


def svd(x, full_matrices=False, name=None):
    return _op("svd", lambda v: tuple(
        jnp.linalg.svd(v, full_matrices=full_matrices)), x)


def eig(x, name=None):
    return _op("eig", lambda v: tuple(jnp.linalg.eig(v)), x)


def _from_triangle(v, UPLO):
    """Symmetric matrix read from one triangle (paddle UPLO semantics)."""
    if UPLO == "L":
        lo = jnp.tril(v)
        return lo + jnp.swapaxes(jnp.tril(v, -1), -1, -2)
    up = jnp.triu(v)
    return up + jnp.swapaxes(jnp.triu(v, 1), -1, -2)


def eigh(x, UPLO="L", name=None):
    return _op("eigh", lambda v: tuple(
        jnp.linalg.eigh(_from_triangle(v, UPLO), symmetrize_input=False)), x)


def eigvals(x, name=None):
    return _op("eigvals", lambda v: jnp.linalg.eigvals(v), x)


def eigvalsh(x, UPLO="L", name=None):
    return _op("eigvalsh", lambda v: jnp.linalg.eigvalsh(
        _from_triangle(v, UPLO)), x)


def solve(x, y, name=None):
    return _op("solve", lambda a, b: jnp.linalg.solve(a, b), x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax.scipy.linalg as jsl
    return _op("triangular_solve",
               lambda a, b: jsl.solve_triangular(
                   a, b, lower=not upper, trans=1 if transpose else 0,
                   unit_diagonal=unitriangular), x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return _op("lstsq", fn, x, y)


def matrix_power(x, n, name=None):
    return _op("matrix_power",
               lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    def fn(v):
        s = (jnp.abs(jnp.linalg.eigvalsh(v)) if hermitian
             else jnp.linalg.svd(v, compute_uv=False))
        if tol is None:
            # numpy default: max(dims) * eps * largest singular value
            t = (max(v.shape[-2:]) * jnp.finfo(v.dtype).eps
                 * jnp.max(s, axis=-1, keepdims=True))
        else:
            t = jnp.asarray(tol)  # paddle: ABSOLUTE tolerance
        return jnp.sum(s > t, axis=-1)
    return _op("matrix_rank", fn, x)


def multi_dot(xs, name=None):
    return _op("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs), *xs)
