"""Serving decode loop: compiled prefill + KV-cache token generation.

This is the TPU replacement for the reference's inference hot path
(AnalysisPredictor decode loop over fused_multi_transformer with its CUDA
KV cache — SURVEY.md §2.2/§3.5): one jitted prefill over the padded prompt
bucket, then a jitted ``lax.scan`` over decode steps, KV cache donated
between steps so generation runs without host round-trips.

Prompt lengths are padded to buckets (powers of two by default) — the
dynamic-shape story on XLA (SURVEY §2.5 CINN row: bucketing/padding
replaces symbolic shapes).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class GenerationConfig:
    max_new_tokens: int = 32
    temperature: float = 1.0
    top_k: int = 0            # 0 = off
    top_p: float = 1.0        # 1.0 = off
    do_sample: bool = False   # False = greedy
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: int = 0


class KVCache:
    """Thin named wrapper over the model's cache pytree (parity surface for
    the reference's CacheKV tensors)."""

    def __init__(self, tree: Any):
        self.tree = tree

    @property
    def seq_capacity(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.tree)
        return leaves[0].shape[2] if leaves else 0


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _sample(logits, key, cfg: GenerationConfig):
    logits = logits.astype(jnp.float32)
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -cfg.top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if cfg.top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest set with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < cfg.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_l, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class GenerationEngine:
    """Compiled generation over a model's (prefill, decode_step, init_cache)
    triple.

    ``prefill(params, ids, cache) -> (logits, cache)``
    ``decode_step(params, tok, pos, cache) -> (logits, cache)``
    ``init_cache(batch, max_len) -> cache pytree``
    """

    def __init__(self, prefill: Callable, decode_step: Callable,
                 init_cache: Callable, config: GenerationConfig = None):
        self._prefill = prefill
        self._decode = decode_step
        self._init_cache = init_cache
        self.config = config or GenerationConfig()
        self._compiled: Dict[Tuple, Callable] = {}

    # -- compiled program per (bucket, max_new) shape ------------------------

    def _build(self, prompt_bucket: int, max_new: int):
        cfg = self.config
        prefill = self._prefill
        decode = self._decode

        def run(params, ids, prompt_len, cache, key):
            # ids: (B, prompt_bucket) right-padded; prompt_len: (B,) uniform
            # (ragged serving batches belong to the paged-attention path,
            # ops/paged_attention.py)
            logits, cache = prefill(params, ids, cache)       # (B, T, V)
            last = jax.lax.dynamic_index_in_dim(
                logits, prompt_len[0] - 1, axis=1, keepdims=False)
            key, sub = jax.random.split(key)
            tok = _sample(last, sub, cfg)

            def step(carry, i):
                tok, cache, key = carry
                pos = prompt_len[0] + i  # uniform-length batch
                lg, cache = decode(params, tok, pos, cache)
                key, sub = jax.random.split(key)
                nxt = _sample(lg, sub, cfg)
                return (nxt, cache, key), tok

            (last, cache, _), toks = jax.lax.scan(
                step, (tok, cache, key), jnp.arange(max_new - 1))
            toks = jnp.concatenate([toks, last[None]], axis=0)  # (max_new, B)
            # Return the final cache so the donated input cache buffers are
            # actually aliasable (donating without returning produced
            # "donated buffers were not usable" warnings and saved nothing).
            return jnp.swapaxes(toks, 0, 1), cache              # (B, max_new)

        return jax.jit(run, donate_argnums=(3,))

    def generate(self, params, input_ids,
                 generation_config: Optional[GenerationConfig] = None):
        """input_ids: (B, T) numpy/jax int array → (B, max_new_tokens)."""
        if generation_config is not None:
            self.config = generation_config
            self._compiled.clear()
        cfg = self.config
        ids = np.asarray(input_ids)
        b, t = ids.shape
        bucket = _bucket(t)
        padded = np.full((b, bucket), cfg.pad_token_id, ids.dtype)
        padded[:, :t] = ids
        # right-padding is safe: pad rows in the cache sit beyond kv_len
        # until decode overwrites each position before first attending to it
        key = (bucket, cfg.max_new_tokens, b)
        if key not in self._compiled:
            self._compiled[key] = self._build(bucket, cfg.max_new_tokens)
        cache = self._init_cache(b, bucket + cfg.max_new_tokens)
        if isinstance(cache, KVCache):
            cache = cache.tree
        prompt_len = jnp.full((b,), t, jnp.int32)
        rng = jax.random.key(cfg.seed)
        out, _ = self._compiled[key](params, jnp.asarray(padded), prompt_len,
                                     cache, rng)
        return np.asarray(out)


def llama_engine(config, generation_config: Optional[GenerationConfig] = None
                 ) -> GenerationEngine:
    """GenerationEngine wired to the stacked-param Llama family."""
    from ..models import llama as L

    return GenerationEngine(
        prefill=functools.partial(_llama_prefill, config=config),
        decode_step=functools.partial(_llama_decode, config=config),
        init_cache=lambda b, s: L.init_kv_cache(config, b, s),
        config=generation_config,
    )


def _llama_prefill(params, ids, cache, config):
    from ..models import llama as L
    return L.prefill_stacked(params, ids, cache, config)


def _llama_decode(params, tok, pos, cache, config):
    from ..models import llama as L
    return L.decode_step_stacked(params, tok, pos, cache, config)


# ---------------------------------------------------------------------------
# Ragged (paged) serving engine
# ---------------------------------------------------------------------------
class PagedGenerationEngine:
    """Ragged-batch generation over the paged KV cache.

    Unlike GenerationEngine (uniform prompt lengths, contiguous cache),
    prompts may have different lengths: each sequence owns pages via a
    block table (ops/paged_attention.py), decode positions advance per row,
    and sampling starts from each row's own last prompt token.
    """

    def __init__(self, model_config, generation_config: Optional[GenerationConfig] = None,
                 page_size: int = 16, num_pages: Optional[int] = None):
        from ..models import llama as L
        self._L = L
        self.model_config = model_config
        self.config = generation_config or GenerationConfig()
        self.page_size = page_size
        self._num_pages = num_pages
        self._compiled: Dict[Tuple, Callable] = {}

    def _build(self, max_new: int):
        L = self._L
        cfg = self.config
        mcfg = self.model_config

        def run(params, ids, seq_lens, k_pages, v_pages, block_tables, key):
            logits, k_pages, v_pages = L.prefill_paged(
                params, ids, seq_lens, k_pages, v_pages, block_tables, mcfg)
            last = jnp.take_along_axis(
                logits, (seq_lens - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                       # (B, V) per-row last token
            key, sub = jax.random.split(key)
            tok = _sample(last, sub, cfg)

            def step(carry, i):
                tok, kp, vp, key = carry
                positions = seq_lens + i            # (B,) per-row position
                lg, kp, vp = L.decode_step_paged(
                    params, tok, positions, kp, vp, block_tables, mcfg)
                key, sub = jax.random.split(key)
                nxt = _sample(lg, sub, cfg)
                return (nxt, kp, vp, key), tok

            (last_tok, k_pages, v_pages, _), toks = jax.lax.scan(
                step, (tok, k_pages, v_pages, key), jnp.arange(max_new - 1))
            toks = jnp.concatenate([toks, last_tok[None]], axis=0)
            return jnp.swapaxes(toks, 0, 1), k_pages, v_pages

        return jax.jit(run, donate_argnums=(3, 4))

    def generate(self, params, prompts):
        """prompts: list of 1-D int arrays (ragged) → (B, max_new_tokens)."""
        from ..ops.paged_attention import PagedKVCacheManager
        cfg = self.config
        mcfg = self.model_config
        lens = [len(p) for p in prompts]
        b = len(prompts)
        t_bucket = _bucket(max(lens))
        ids = np.full((b, t_bucket), cfg.pad_token_id, np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = np.asarray(p, np.int32)

        total = [l + cfg.max_new_tokens for l in lens]
        pages_per_seq = [(n + self.page_size - 1) // self.page_size
                         for n in total]
        num_pages = self._num_pages or (sum(pages_per_seq) + 1)
        mgr = PagedKVCacheManager(
            mcfg.num_hidden_layers, num_pages, self.page_size,
            mcfg.num_key_value_heads, mcfg.head_dim, dtype=mcfg.dtype)
        for i in range(b):
            mgr.allocate(i, total[i])
            mgr._lens[i] = lens[i]  # prompt length is the live length
        bt, seq_lens = mgr.block_tables(list(range(b)))

        key = (t_bucket, cfg.max_new_tokens, b, bt.shape[1])
        if key not in self._compiled:
            self._compiled[key] = self._build(cfg.max_new_tokens)
        rng = jax.random.key(cfg.seed)
        toks, _, _ = self._compiled[key](
            params, jnp.asarray(ids), jnp.asarray(seq_lens, jnp.int32),
            mgr.k_pages, mgr.v_pages, jnp.asarray(bt), rng)
        return np.asarray(toks)
