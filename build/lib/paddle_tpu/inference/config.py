"""Inference Config (parity: paddle.inference.Config).

Reference: paddle/fluid/inference/api/analysis_config.cc pybind surface.
GPU/TensorRT/IR knobs are accepted for API compatibility; on TPU they map
to XLA (which always "fuses") or are recorded no-ops.
"""

from __future__ import annotations

from typing import Optional


class Config:
    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        # jit.save triple prefix: model_path may be "<prefix>" or
        # "<prefix>.pdmodel" (reference passes model+params separately)
        if model_path and model_path.endswith(".pdmodel"):
            model_path = model_path[: -len(".pdmodel")]
        self._prefix = model_path
        self._device = "tpu"
        self._device_id = 0
        self._enable_memory_optim = True
        self._switch_ir_optim = True
        self._cache_dir: Optional[str] = None

    # -- model location ------------------------------------------------------
    def set_prog_file(self, path: str) -> None:
        if path.endswith(".pdmodel"):
            path = path[: -len(".pdmodel")]
        self._prefix = path

    def prog_file(self) -> str:
        return (self._prefix or "") + ".pdmodel"

    def params_file(self) -> str:
        return (self._prefix or "") + ".pdiparams"

    def set_model(self, model_path: str, params_path: Optional[str] = None):
        self.set_prog_file(model_path)

    # -- device --------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100,
                       device_id: int = 0):
        """Parity alias: selects the accelerator (TPU here)."""
        self._device = "tpu"
        self._device_id = device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self) -> bool:
        return self._device != "cpu"

    def set_cpu_math_library_num_threads(self, n: int):
        pass

    # -- optimisation knobs (XLA owns these; recorded no-ops) ----------------
    def switch_ir_optim(self, flag: bool = True):
        self._switch_ir_optim = flag

    def enable_memory_optim(self, flag: bool = True):
        self._enable_memory_optim = flag

    def enable_tensorrt_engine(self, *args, **kwargs):
        """TensorRT has no TPU analog; XLA is the compiler (SURVEY §2.5)."""

    def enable_tuned_tensorrt_dynamic_shape(self, *args, **kwargs):
        pass

    def set_optim_cache_dir(self, path: str):
        self._cache_dir = path

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix}, device={self._device}, "
                f"ir_optim={self._switch_ir_optim})")
