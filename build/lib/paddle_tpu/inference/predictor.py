"""Predictor: run a loaded inference program through named IO handles.

Reference: AnalysisPredictor + ZeroCopyTensor (paddle/fluid/inference/api/
analysis_predictor.cc, details_zero_copy_tensor ⚠ — SURVEY.md §3.5):
``get_input_handle(name).copy_from_cpu(arr); predictor.run();
out = get_output_handle(name).copy_to_cpu()``.

"Zero-copy" TPU reading: ``copy_from_cpu`` stages the host array once
(device transfer happens at dispatch); outputs stay on device until
``copy_to_cpu`` materialises them.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..jit.save_load import TranslatedLayer, load as _jit_load
from .config import Config


class IOTensor:
    """ZeroCopyTensor parity handle."""

    def __init__(self, name: str):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr) -> None:
        self._value = np.ascontiguousarray(arr)

    def share_external_data(self, arr) -> None:
        self._value = arr  # no copy: jax array / dlpack-compatible

    def reshape(self, shape) -> None:
        if self._value is not None:
            self._value = np.reshape(self._value, shape)

    def copy_to_cpu(self):
        import jax
        v = self._value
        return np.asarray(v) if isinstance(v, jax.Array) else v

    def shape(self):
        return None if self._value is None else tuple(self._value.shape)


class Predictor:
    def __init__(self, config: Config, program: Optional[TranslatedLayer] = None):
        self._config = config
        self._program = program or _jit_load(config._prefix)
        n_in = len(self._program.input_spec)
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._inputs: Dict[str, IOTensor] = {
            n: IOTensor(n) for n in self._input_names}
        self._output_names: List[str] = []
        self._outputs: Dict[str, IOTensor] = {}

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> IOTensor:
        return self._inputs[name]

    def run(self, inputs: Optional[List] = None):
        """Execute. Either feed via handles then ``run()``, or pass arrays
        directly (newer reference API) and get arrays back."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(a))
        args = [self._inputs[n]._value for n in self._input_names]
        if any(a is None for a in args):
            missing = [n for n in self._input_names
                       if self._inputs[n]._value is None]
            raise ValueError(f"inputs not set: {missing}")
        out = self._program(*args)
        outs = out if isinstance(out, tuple) else (out,)
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {}
        for n, o in zip(self._output_names, outs):
            h = IOTensor(n)
            h._value = o._value
            self._outputs[n] = h
        if inputs is not None:
            return [np.asarray(o._value) for o in self._outputs.values()]
        return True

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> IOTensor:
        return self._outputs[name]

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
